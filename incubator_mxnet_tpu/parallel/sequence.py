"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference (MXNet v0.11) predates attention entirely — its long-sequence
story is bucketing + truncated BPTT (SURVEY.md §5.7).  The capability row
to match is "scale sequence length"; on TPU the idiomatic designs are:

- **ring attention** (`ring_attention`): Q stays resident, K/V blocks
  rotate around the mesh axis via ``lax.ppermute`` (ICI neighbor hops)
  while a streaming/flash-style online softmax accumulates the output —
  memory per chip is O(seq/n), and the K/V hop overlaps with the local
  block matmul.
- **Ulysses / all-to-all** (`ulysses_attention`): ``lax.all_to_all``
  re-shards seq→heads, full attention runs locally per head group, then
  heads→seq restores the layout.  Cheaper collectives for moderate
  sequence lengths when heads ≥ mesh axis.

Both are shard_map-ready: call them inside ``shard_map`` with the sequence
axis sharded over ``axis_name``; `sequence_parallel_attention` wraps that
for convenience.  Shapes follow (batch, heads, seq, head_dim).
"""
from __future__ import annotations

import functools
from typing import Optional

from .mesh import axis_size as _axis_size

__all__ = ["attention", "flash_eligible", "ring_attention",
           "ulysses_attention", "sequence_parallel_attention"]


def flash_eligible(q_shape, k_shape) -> bool:
    """True when ``attention(impl='auto')`` would take the Pallas flash
    path for these shapes (TPU backend, 4-D, lane-aligned head_dim and
    seq lens).  THE gate — shared with ``tools/bench_lm.py``'s
    executed-FLOPs accounting so the causal halving can never drift
    from the kernel actually run."""
    import jax

    # 'axon' is this session's TPU-via-tunnel platform name
    return (jax.default_backend() in ("tpu", "axon")
            and len(q_shape) == 4 and q_shape[-1] % 128 == 0
            and q_shape[-2] % 128 == 0 and k_shape[-2] % 128 == 0)


def _neg_inf(dtype):
    import jax.numpy as jnp
    import numpy as np

    return jnp.asarray(np.finfo(np.dtype(dtype).name if
                                np.dtype(dtype).kind == "f"
                                else "float32").min, dtype)


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
              q_offset=0, k_offset=0, impl: str = "auto"):
    """Softmax attention on local shards (the oracle and the building
    block).  ``q_offset``/``k_offset`` are the GLOBAL positions of the
    first row/column — causal masking stays correct when q and k are
    shards of a longer sequence.

    ``impl``: ``"xla"`` materializes the score matrix (the oracle);
    ``"flash"`` uses the Pallas TPU flash-attention kernel (O(s) memory —
    measured on-chip: s=16384 runs where the materialized path OOMs,
    PERF.md); ``"auto"`` picks flash on a TPU backend when the shape
    qualifies (4-D, no offsets, lane-aligned head_dim).
    """
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    # offsets may be TRACED values (lax.axis_index arithmetic under
    # shard_map) — only CONCRETE zeros qualify for the flash path
    def _zero(off):
        import numpy as np

        if isinstance(off, (int, np.integer)):
            return int(off) == 0
        try:
            return bool(off == 0)  # concrete array scalars
        except Exception:  # traced value: not concretizable
            return False

    use_flash = impl == "flash"
    if use_flash and not (_zero(q_offset) and _zero(k_offset)):
        raise ValueError("impl='flash' does not support q_offset/"
                         "k_offset (the kernel masks from local "
                         "position 0); use impl='xla' for shard-offset "
                         "causal masking")
    if impl == "auto":
        use_flash = (_zero(q_offset) and _zero(k_offset)
                     and flash_eligible(q.shape, k.shape))
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes, flash_attention)

        # kernel defaults (128-blocks) underuse the MXU: a 512-block
        # sweep measured 3.0x faster fwd+bwd at B=8,H=16,S=2048,D=128
        # on v5e (17ms vs 51ms; 1024 and mixed blocks were worse) —
        # PERF.md §11.  Blocks must divide the (128-aligned) seq lens.
        def _blk(s):
            return max(b for b in (512, 256, 128) if s % b == 0)

        bq, bk = _blk(q.shape[-2]), _blk(k.shape[-2])
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
            block_q_dq=bq)
        return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                               block_sizes=bs)
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-2])
        ki = k_offset + jnp.arange(k.shape[-2])
        s = jnp.where(qi[:, None] >= ki[None, :], s, _neg_inf(s.dtype))
    p = jnp.exp(s - s.max(-1, keepdims=True))
    return jnp.einsum("...qk,...kd->...qd", p / p.sum(-1, keepdims=True),
                      v)


def _online_block_update(q32, kb, vb, m, l, o, causal, scale, qi, k0,
                         neg):
    """One flash-style online-softmax update with key block ``kb``/``vb``
    whose first key has GLOBAL position ``k0``.  The unit both the ring
    hop and its sub-hop chunks share."""
    import jax.numpy as jnp

    s = jnp.einsum("...qd,...kd->...qk", q32,
                   kb.astype(jnp.float32)) * scale
    if causal:
        ki = k0 + jnp.arange(kb.shape[-2])
        mask = qi[:, None] >= ki[None, :]
        s = jnp.where(mask, s, neg)
    m_new = jnp.maximum(m, s.max(-1))
    # fully-masked rows: keep exp argument finite
    p = jnp.exp(s - jnp.where(m_new == neg, 0.0, m_new)[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.where(m == neg, 0.0,
                     jnp.exp(m - jnp.where(m_new == neg, 0.0, m_new)))
    l = l * corr + p.sum(-1)
    o = o * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, vb.astype(jnp.float32))
    return m_new, l, o


def _hop_chunks(block_len: int, hop_chunk: int) -> int:
    """Number of sub-chunks a hop's K/V block is processed in (1 = the
    dense whole-block path).  Chunking keeps the per-hop (bq × chunk)
    f32 score temp O(block) instead of O(shard²) — at S/n = 8k the
    dense block temp is 256 MB+ f32 (round-4 verdict #6).

    Non-divisible shard lengths use the largest divisor ≤ hop_chunk so
    the memory bound survives (no silent dense fallback); only
    pathological lengths whose best divisor is tiny (< 128 — prime-ish
    and lane-unaligned anyway) fall back to dense."""
    if not hop_chunk or block_len <= hop_chunk:
        return 1
    for c in range(int(hop_chunk), 0, -1):
        if block_len % c == 0:
            if c < min(128, block_len):
                return 1
            return block_len // c
    return 1


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   hop_chunk: int = 1024):
    """Ring self-attention over a sharded sequence axis.

    Call inside shard_map: q/k/v are the LOCAL sequence shards
    (batch, heads, seq/n, d).  K/V rotate n−1 hops around the ring
    (``ppermute``); an online softmax (running max ``m``, normalizer
    ``l``, accumulator ``o`` — the flash-attention recurrence) makes the
    streaming accumulation exact, not approximate.

    Training-safe: a ``jax.custom_vjp`` backward runs a SECOND ring pass
    that recomputes each hop's score block from the saved per-row
    logsumexp (the flash-attention backward) with the dK/dV accumulators
    riding the ring alongside their K/V blocks — per-device memory stays
    O(seq/n) in backward too, instead of reverse-mode-through-
    ``fori_loop`` checkpointing every hop's rotated K/V (O(global seq),
    the round-3 VERDICT §5.7 gap).

    ``hop_chunk``: each hop's K/V block is streamed through the online
    softmax in ≤hop_chunk-key tiles (when the block divides), so the
    per-hop f32 score temp is (bq × hop_chunk), O(block), instead of
    the full (S/n × S/n) — the round-4 verdict #6 constant.  0
    disables (dense whole-block hops)."""
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    return _ring_attention_vjp(axis_name, bool(causal), float(scale),
                               int(hop_chunk))(q, k, v)


def _ring_fwd_pass(q, k, v, axis_name, causal, scale, hop_chunk):
    """Online-softmax ring forward; returns (out, lse) with lse the
    per-row logsumexp of the GLOBAL score row (the flash residual)."""
    import jax.numpy as jnp
    from jax import lax

    n = _axis_size(axis_name)
    # the device index feeds only the causal-mask offsets; emitting it
    # unmasked leaves an orphan PartitionId the SPMD partitioner rejects
    # (CPU backend), so only materialize it when the mask is on
    idx = lax.axis_index(axis_name) if causal else jnp.int32(0)
    bq = q.shape[-2]
    bk = k.shape[-2]
    neg = _neg_inf(jnp.float32)
    nc = _hop_chunks(bk, hop_chunk)
    chunk = bk // nc

    q32 = q.astype(jnp.float32)
    # derive the carries from q so they inherit its varying ('sp') axes —
    # fresh jnp.zeros would be unvarying and reject the scan carry
    m = jnp.full_like(q32[..., 0], neg)
    l = jnp.zeros_like(q32[..., 0])
    o = jnp.zeros_like(q32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    q_off = idx * bq
    qi = q_off + jnp.arange(bq)

    def body(step, carry):
        kk, vv, m, l, o = carry
        # block (kk, vv) originated on ring neighbor (idx - step) mod n
        owner = (idx - step) % n

        def one_chunk(c, mlo):
            kb = lax.dynamic_slice_in_dim(kk, c * chunk, chunk, -2)
            vb = lax.dynamic_slice_in_dim(vv, c * chunk, chunk, -2)
            return _online_block_update(
                q32, kb, vb, *mlo, causal, scale, qi,
                owner * bk + c * chunk, neg)

        # streaming the hop's block in chunks keeps the f32 score temp
        # (bq × chunk) instead of (bq × bk) — O(block) at long shards
        m, l, o = lax.fori_loop(0, nc, one_chunk, (m, l, o))
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return kk, vv, m, l, o

    _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m, l, o))
    out = (o / jnp.where(l == 0.0, 1.0, l)[..., None]).astype(q.dtype)
    # fully-masked rows (l == 0): lse = +inf so exp(s - lse) == 0 in bwd
    lse = jnp.where(l == 0.0, jnp.inf, m + jnp.log(
        jnp.where(l == 0.0, 1.0, l)))
    return out, lse


@functools.lru_cache(maxsize=None)
def _ring_attention_vjp(axis_name, causal, scale, hop_chunk):
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.custom_vjp
    def f(q, k, v):
        return _ring_fwd_pass(q, k, v, axis_name, causal, scale,
                              hop_chunk)[0]

    def f_fwd(q, k, v):
        out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, scale,
                                  hop_chunk)
        return out, (q, k, v, out, lse)

    def f_bwd(res, do):
        q, k, v, out, lse = res
        n = _axis_size(axis_name)
        # see _ring_fwd_pass: axis_index only when the mask consumes it
        idx = lax.axis_index(axis_name) if causal else jnp.int32(0)
        bq = q.shape[-2]
        bk = k.shape[-2]
        neg = _neg_inf(jnp.float32)
        nc = _hop_chunks(bk, hop_chunk)
        chunk = bk // nc
        q32 = q.astype(jnp.float32)
        do32 = do.astype(jnp.float32)
        # delta[r] = Σ_d dO[r,d]·O[r,d] — the softmax-jacobian row term
        delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)

        perm = [(i, (i + 1) % n) for i in range(n)]
        q_off = idx * bq
        qi = q_off + jnp.arange(bq)
        dq0 = jnp.zeros_like(q32)
        dk0 = jnp.zeros_like(q32, shape=k.shape)
        dv0 = jnp.zeros_like(q32, shape=v.shape)

        def body(step, carry):
            kk, vv, dk, dv, dq = carry
            owner = (idx - step) % n

            def one_chunk(c, acc):
                dk, dv, dq = acc
                off = c * chunk
                kb = lax.dynamic_slice_in_dim(kk, off, chunk, -2) \
                    .astype(jnp.float32)
                vb = lax.dynamic_slice_in_dim(vv, off, chunk, -2) \
                    .astype(jnp.float32)
                s = jnp.einsum("...qd,...kd->...qk", q32, kb) * scale
                if causal:
                    ki = owner * bk + off + jnp.arange(chunk)
                    s = jnp.where(qi[:, None] >= ki[None, :], s, neg)
                # exact probabilities from the saved logsumexp
                p = jnp.exp(s - lse[..., None])
                dv_b = jnp.einsum("...qk,...qd->...kd", p, do32)
                dp = jnp.einsum("...qd,...kd->...qk", do32, vb)
                ds = p * (dp - delta[..., None]) * scale
                dq = dq + jnp.einsum("...qk,...kd->...qd", ds, kb)
                dk_b = jnp.einsum("...qk,...qd->...kd", ds, q32)
                dk = lax.dynamic_update_slice_in_dim(
                    dk, lax.dynamic_slice_in_dim(dk, off, chunk, -2)
                    + dk_b, off, -2)
                dv = lax.dynamic_update_slice_in_dim(
                    dv, lax.dynamic_slice_in_dim(dv, off, chunk, -2)
                    + dv_b, off, -2)
                return dk, dv, dq

            # chunked like the forward: the per-hop f32 score/p/ds
            # temps stay (bq × chunk), O(block), at long shards
            dk, dv, dq = lax.fori_loop(0, nc, one_chunk, (dk, dv, dq))
            # dK/dV accumulators travel WITH their block: after n hops
            # they are back home with every device's contribution
            kk = lax.ppermute(kk, axis_name, perm)
            vv = lax.ppermute(vv, axis_name, perm)
            dk = lax.ppermute(dk, axis_name, perm)
            dv = lax.ppermute(dv, axis_name, perm)
            return kk, vv, dk, dv, dq

        _, _, dk, dv, dq = lax.fori_loop(
            0, n, body, (k, v, dk0, dv0, dq0))
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Inside shard_map with seq sharded on ``axis_name``: all_to_all trades
    the seq shard for a heads shard (heads must divide by the axis size),
    attention runs over the FULL sequence locally, and a reverse
    all_to_all restores the seq sharding.
    """
    from jax import lax

    n = _axis_size(axis_name)
    if q.shape[1] % n:
        raise ValueError("heads (%d) must be divisible by axis size %d"
                         % (q.shape[1], n))
    # (b, h, s/n, d) → (b, h/n, s, d): split heads, concat sequence
    def fwd(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = fwd(q), fwd(k), fwd(v)
    out = attention(qg, kg, vg, causal=causal, scale=scale)
    # (b, h/n, s, d) → (b, h, s/n, d)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def sequence_parallel_attention(mesh, q, k, v, axis_name: str = "sp",
                                causal: bool = False,
                                scale: Optional[float] = None,
                                mode: str = "ring",
                                hop_chunk: int = 1024):
    """Jit-compiled sequence-parallel attention over ``mesh``.

    q/k/v are GLOBAL arrays (b, h, s, d); the sequence axis is sharded
    over ``axis_name`` and the chosen kernel (``ring`` or ``ulysses``)
    runs under shard_map.  ``hop_chunk`` tunes/disables the ring's
    per-hop streaming tiles (ignored by ulysses).
    """
    import jax

    from .mesh import shard_map_fn

    shard_map = shard_map_fn()

    P = jax.sharding.PartitionSpec
    spec = P(None, None, axis_name, None)
    if mode == "ring":
        fn = functools.partial(ring_attention, axis_name=axis_name,
                               causal=causal, scale=scale,
                               hop_chunk=hop_chunk)
    else:
        fn = functools.partial(ulysses_attention, axis_name=axis_name,
                               causal=causal, scale=scale)
    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return jax.jit(sharded)(q, k, v)
