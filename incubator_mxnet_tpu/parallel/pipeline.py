"""Pipeline parallelism (pp): stages laid out over a mesh axis.

The reference's only model parallelism is manual layer placement via
``group2ctx`` + ``_CrossDeviceCopy`` (``graph_executor.cc:279-393``),
demonstrated by the model-parallel LSTM example
(``example/model-parallel-lstm/lstm.py:65-68``).  The TPU-native
generalization is a collective-permute pipeline: device *i* holds stage
*i*'s parameters, microbatches flow device→device over ICI via
``lax.ppermute`` inside one jitted program (GPipe schedule: M + L − 1
ticks for M microbatches through L stages), so stage compute and the
activation hop overlap the way ``_CrossDeviceCopy`` engine ops did.

Two layers:

- ``pipeline_apply`` / ``pipeline_parallel_apply``: the generic
  forward utility (uniform stage_fn, replicated microbatches) — kept
  for toy stage functions and the multi-axis dryrun.
- ``PipelineTrainStep``: REAL pipelined training of the transformer-LM
  family — full fwd+bwd+optimizer in one jitted SPMD program.
  Microbatch TOKENS (not activations) are injected, the loss is taken
  from the last stage only (scalar psum — no L× activation broadcast),
  every stage tick is ``jax.checkpoint``-ed so in-flight residuals stay
  at one boundary activation per tick (the memory property 1F1B
  targets, obtained here by recompute under the GPipe order), and
  gradients accumulate over microbatches inside the program.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import numpy as np

from .mesh import axis_size as _axis_size

__all__ = ["pipeline_apply", "pipeline_parallel_apply",
           "PipelineTrainStep", "pp_bubble_fraction", "pp_schedule",
           "PP_SCHEDULES"]

# the schedules the symbol pipeline engine knows how to table out
PP_SCHEDULES = ("gpipe", "1f1b")


def pp_bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Steady-state pipeline bubble fraction (L−1)/(M+L−1).

    Identical for GPipe and 1F1B (Narayanan et al., SC'21 §2.2) — the
    two schedules differ in *memory* (in-flight activations per stage:
    M vs ≤ L−s), not in idle-tick count.
    """
    L, M = int(n_stages), int(n_microbatches)
    return (L - 1) / float(M + L - 1)


def pp_schedule(schedule: str, n_stages: int, n_microbatches: int):
    """Tick tables for the SPMD symbol-pipeline engine.

    Both schedules run the same T = 2·(M+L−1) ticks (M forward and M
    backward ops per stage plus (L−1) fwd + (L−1) bwd bubble ticks);
    what differs is WHEN each stage runs which op:

    - ``gpipe`` (Huang et al., NeurIPS'19): all forwards first —
      F(s,m) = s+m, then all backwards — B(s,m) = (M+L−1)+(L−1−s)+m.
      Every stage stashes all M boundary inputs.
    - ``1f1b`` (Narayanan et al., SC'21): stage s runs L−1−s warm-up
      forwards (F = s+m), then alternates one-forward-one-backward
      (F(s,m) = s+2m, B(s,m) = 2L−1−s+2m), then drains.  At most
      L−s microbatches are in flight at stage s, so min(L, M) stash
      slots suffice — reused round-robin by ``m % n_slots``.

    Returns ``(op, mb, arrive, n_slots)``: int32 numpy arrays of shape
    (T, L).  ``op[t, s]`` is 0 idle / 1 forward / 2 backward;
    ``mb[t, s]`` the microbatch index of that op; ``arrive[t, s]`` the
    stash slot receiving the boundary activation hopping in from stage
    s−1 this tick (= ``n_slots``, a scratch row, when none arrives).
    Dependency timing is exact by construction: the boundary for
    (s, m) lands at tick F(s−1,m)+1 ≤ F(s,m), and the cotangent for
    (s, m) lands at tick B(s+1,m)+1 = B(s,m).
    """
    L, M = int(n_stages), int(n_microbatches)
    if schedule == "gpipe":
        n_slots = M

        def fwd_tick(s, m):
            return s + m

        def bwd_tick(s, m):
            return (M + L - 1) + (L - 1 - s) + m
    elif schedule == "1f1b":
        n_slots = min(L, M)

        def fwd_tick(s, m):
            return s + m if m < L - 1 - s else s + 2 * m

        def bwd_tick(s, m):
            return 2 * L - 1 - s + 2 * m
    else:
        raise ValueError("unknown pipeline schedule %r (one of %s)"
                         % (schedule, ", ".join(PP_SCHEDULES)))

    T = 2 * (M + L - 1)
    op = np.zeros((T, L), np.int32)
    mb = np.zeros((T, L), np.int32)
    arrive = np.full((T, L), n_slots, np.int32)
    for s in range(L):
        for m in range(M):
            tf, tb = fwd_tick(s, m), bwd_tick(s, m)
            if op[tf, s] or op[tb, s] or tf >= tb:
                raise ValueError(
                    "internal: %s schedule conflict at stage %d mb %d"
                    % (schedule, s, m))
            op[tf, s], mb[tf, s] = 1, m
            op[tb, s], mb[tb, s] = 2, m
            if s + 1 < L:
                arrive[tf + 1, s + 1] = m % n_slots
    return op, mb, arrive, n_slots


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches,
                   axis_name: str = "pp"):
    """Run microbatches through the stage pipeline (shard_map body).

    stage_fn(params, x) -> y with ``y.shape == x.shape``; stage_params is
    the LOCAL stage's parameter pytree (sharded over ``axis_name`` by the
    caller); ``x_microbatches`` (M, ...) is replicated — device 0 injects
    microbatch t at tick t, device L−1 collects the finished microbatch
    at tick t ≥ L−1.  Returns (M, ...) outputs, replicated via a final
    psum so every stage sees the result (loss is usually computed on the
    last stage; replication keeps the API simple at toy scale).
    """
    import jax.numpy as jnp
    from jax import lax

    L = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    perm = [(i, i + 1) for i in range(L - 1)]  # no wraparound: a chain

    # the carries must be marked device-varying over the pipeline axis
    # (the loop writes per-stage values into them); fresh zeros would be
    # unvarying and rejected as a scan carry under shard_map
    state = jnp.zeros_like(x_microbatches[0])
    outs = jnp.zeros_like(x_microbatches)
    if hasattr(lax, "pcast"):
        state = lax.pcast(state, (axis_name,), to="varying")
        outs = lax.pcast(outs, (axis_name,), to="varying")

    def tick(t, carry):
        state, outs = carry
        # device 0 injects microbatch t (a dummy repeat past the end —
        # masked out downstream because its result never lands in a slot)
        inj = x_microbatches[jnp.minimum(t, M - 1)]
        x_in = jnp.where(idx == 0, inj, state)
        y = stage_fn(stage_params, x_in)
        # last device banks finished microbatch (slot = t − (L−1))
        slot = t - (L - 1)
        take = (idx == L - 1) & (slot >= 0) & (slot < M)
        safe = jnp.clip(slot, 0, M - 1)
        outs = outs.at[safe].set(jnp.where(take, y, outs[safe]))
        state = lax.ppermute(y, axis_name, perm)
        return state, outs

    _, outs = lax.fori_loop(0, M + L - 1, tick, (state, outs))
    # only the last stage holds real outputs; replicate
    return lax.psum(jnp.where(idx == L - 1, outs, 0.0), axis_name)


def pipeline_parallel_apply(mesh, stage_fn: Callable, stacked_params,
                            x_microbatches, axis_name: str = "pp"):
    """Jit-compiled pipeline over ``mesh``.

    stacked_params: pytree whose leaves have a leading stage dim (L, ...)
    — sharded one stage per device over ``axis_name``; x_microbatches
    (M, ...) replicated.

    The jitted program is cached per (mesh, stage_fn, axis_name) — pass a
    STABLE ``stage_fn`` (module-level function, not a fresh lambda per
    call) or every call retraces and recompiles.
    """
    fn = _build_pipeline(mesh, stage_fn, axis_name,
                         jax_tree_structure(stacked_params))
    return fn(stacked_params, x_microbatches)


def jax_tree_structure(tree):
    import jax

    return jax.tree.structure(tree)


@functools.lru_cache(maxsize=64)
def _build_pipeline(mesh, stage_fn, axis_name, params_treedef):
    """Cached jitted pipeline — a fresh closure per call would defeat
    jax.jit's cache and retrace/recompile every step."""
    import jax

    from .mesh import shard_map_fn

    P = jax.sharding.PartitionSpec

    def body(params, x):
        import jax.numpy as jnp

        local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        return pipeline_apply(stage_fn, local, x, axis_name)

    spec_p = jax.tree.unflatten(
        params_treedef, [P(axis_name)] * params_treedef.num_leaves)
    kwargs = {}
    from jax import lax
    if not hasattr(lax, "pcast"):
        # pre-pcast jax cannot mark the scan carries device-varying (see
        # pipeline_apply) and its replication checker then rejects them
        # under grad — disable the check, per jax's own suggestion
        kwargs["check_rep"] = False
    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(spec_p, P()), out_specs=P(), **kwargs)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# PipelineTrainStep: real pipelined training for the transformer-LM family
# ---------------------------------------------------------------------------

def _pp_layer_norm(x, g, b):
    # the REGISTERED LayerNorm op (ops/nn.py) — single source of truth
    # for norm semantics (f32 stats, cast back to activation dtype)
    from ..ops.registry import OpContext, get_op

    (y,), _ = get_op("LayerNorm").apply(
        [x, g, b], {"axis": "-1"}, OpContext(is_train=True))
    return y


def _pp_fc(x, w, b=None):
    # the REGISTERED FullyConnected op (ops/nn.py) — single source of
    # truth for the y = x·Wᵀ (+bias) dtype/cast rules
    from ..ops.registry import OpContext, get_op

    attrs = {"num_hidden": str(w.shape[0]), "flatten": "False",
             "no_bias": str(b is None)}
    ins = [x, w] if b is None else [x, w, b]
    (y,), _ = get_op("FullyConnected").apply(
        ins, attrs, OpContext(is_train=True))
    return y


def _pp_block(x, p, heads, causal, attn_impl):
    """One pre-norm transformer block, matching models/transformer.py
    (same ops, same order) so pipelined training is numerically the
    symbol model's training."""
    import jax.numpy as jnp

    from .sequence import attention

    bsz, seq, embed = x.shape
    d = embed // heads
    ln1 = _pp_layer_norm(x, p["ln1_gamma"], p["ln1_beta"])

    def split(t):
        return t.reshape(bsz, seq, heads, d).transpose(0, 2, 1, 3)

    q = split(_pp_fc(ln1, p["q_weight"]))
    k = split(_pp_fc(ln1, p["k_weight"]))
    v = split(_pp_fc(ln1, p["v_weight"]))
    att = attention(q, k, v, causal=causal, impl=attn_impl)
    att = att.transpose(0, 2, 1, 3).reshape(bsz, seq, embed)
    x = x + _pp_fc(att, p["attn_proj_weight"], p["attn_proj_bias"])
    ln2 = _pp_layer_norm(x, p["ln2_gamma"], p["ln2_beta"])
    h = _pp_fc(ln2, p["ffn1_weight"], p["ffn1_bias"])
    h = jnp.maximum(h, 0)
    return x + _pp_fc(h, p["ffn2_weight"], p["ffn2_bias"])


_PP_BLOCK_LEAVES = (
    ("ln1_gamma", "E", 1.0), ("ln1_beta", "E", 0.0),
    ("q_weight", "EE", None), ("k_weight", "EE", None),
    ("v_weight", "EE", None),
    ("attn_proj_weight", "EE", None), ("attn_proj_bias", "E", 0.0),
    ("ln2_gamma", "E", 1.0), ("ln2_beta", "E", 0.0),
    ("ffn1_weight", "4EE", None), ("ffn1_bias", "4E", 0.0),
    ("ffn2_weight", "E4E", None), ("ffn2_bias", "E", 0.0),
)


class PipelineTrainStep:
    """Pipelined transformer-LM training over a ``pp`` mesh axis — the
    trainer the round-3 forward-only utility was not.

    One jitted SPMD program per step: a GPipe tick loop under shard_map
    (M microbatches, L = pp-axis stages, M + L − 1 ticks) with
    - microbatch TOKENS injected at stage 0 (embedding computed in-tick;
      no replicated activation broadcast),
    - per-tick ``jax.checkpoint`` (in-flight residual = one boundary
      activation per tick — the memory property 1F1B schedules target,
      obtained by recompute under the GPipe order),
    - the fused chunked softmax-xent head on the LAST stage only
      (non-final stages feed the head zeros, whose dW is exactly zero,
      so the replicated head gradient psum stays correct),
    - gradient accumulation across microbatches inside the program and
      the same fused optimizer ops as ``FusedTrainStep``.

    Reference parity anchor: ``example/model-parallel-lstm/lstm.py:65-68``
    (manual per-device layer placement); here the schedule, transfers
    and grad accumulation are compiler-visible XLA collectives.
    """

    def __init__(self, mesh, vocab_size, embed, heads, num_layers,
                 seq_len, batch_size, num_microbatches,
                 dtype: str = "float32", attn_impl: str = "auto",
                 causal: bool = True, optimizer: str = "adam",
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 initializer=None, axis_name: str = "pp"):
        import jax
        import jax.numpy as jnp

        if batch_size % num_microbatches:
            raise ValueError("batch_size %d must divide into %d "
                             "microbatches" % (batch_size,
                                               num_microbatches))
        self.mesh = mesh
        npp = mesh.shape[axis_name]
        if num_layers % npp:
            raise ValueError("num_layers %d must divide over %d pipeline "
                             "stages" % (num_layers, npp))
        self.axis_name = axis_name
        # every OTHER mesh axis is data parallelism: the per-microbatch
        # batch shards over it (dp x pp composition); grads of the
        # pp-sharded block stacks and replicated embed/head params are
        # psummed over it by the shard_map transpose
        self._data_axes = tuple(a for a in mesh.axis_names
                                if a != axis_name)
        ndp = 1
        for a in self._data_axes:
            ndp *= mesh.shape[a]
        if (batch_size // num_microbatches) % max(ndp, 1):
            raise ValueError(
                "microbatch size %d must shard over %d data-parallel "
                "devices" % (batch_size // num_microbatches, ndp))
        self._ndp = ndp
        self.cfg = dict(vocab_size=vocab_size, embed=embed, heads=heads,
                        num_layers=num_layers, seq_len=seq_len,
                        batch_size=batch_size,
                        num_microbatches=num_microbatches, dtype=dtype,
                        attn_impl=attn_impl, causal=causal)

        # ---- optimizer (FusedTrainStep's resolution, compact) --------
        opt_params = dict(optimizer_params or {})
        self.lr = float(opt_params.pop("learning_rate", 0.01))
        momentum = float(opt_params.get("momentum", 0.0))
        if optimizer == "sgd":
            if momentum != 0.0:
                self._opt_op, self._n_states = "sgd_mom_update", 1
            else:
                self._opt_op, self._n_states = "sgd_update", 0
                opt_params.pop("momentum", None)
        elif optimizer == "adam":
            self._opt_op, self._n_states = "adam_update", 2
        else:
            raise ValueError("PipelineTrainStep supports sgd/adam, got %s"
                             % optimizer)
        opt_params.setdefault("rescale_grad", 1.0 / batch_size)
        self._opt_attrs = opt_params
        self._adam_b1 = float(opt_params.get("beta1", 0.9))
        self._adam_b2 = float(opt_params.get("beta2", 0.999))
        self.num_update = 0

        # ---- parameters (symbol-compatible names) --------------------
        from ..initializer import InitDesc, Uniform
        from .fused import _HostInitBuffer

        initializer = initializer or Uniform(0.01)

        def host_init(name, shape):
            # host numpy, never a device scratch: on-device zeros +
            # setitem would compile per shape over the tunnel and the
            # final device_put round-trips D2H (see fused.host_init)
            arr = _HostInitBuffer(shape)
            try:
                initializer(InitDesc(name), arr)
                return arr._np
            except Exception:
                from ..ndarray import zeros as nd_zeros

                nd = nd_zeros(shape)
                initializer(InitDesc(name), nd)
                return np.asarray(nd.data)

        E, V, S = embed, vocab_size, seq_len
        dims = {"E": (E,), "EE": (E, E), "4EE": (4 * E, E),
                "4E": (4 * E,), "E4E": (E, 4 * E)}
        blocks = {}
        for leaf, dim, fill in _PP_BLOCK_LEAVES:
            per = []
            for i in range(num_layers):
                # gamma/beta get their reference-init constants; weights
                # go through the initializer under their symbol name
                name = "block%d_%s" % (i, leaf)
                if fill is not None:
                    per.append(np.full(dims[dim], fill, np.float32))
                else:
                    per.append(host_init(name, dims[dim]))
            blocks[leaf] = np.stack(per)
        self._rep = {
            "tok_embed_weight": host_init("tok_embed_weight", (V, E)),
            "pos_embed_weight": host_init("pos_embed_weight", (S, E)),
            "ln_f_gamma": np.ones((E,), np.float32),
            "ln_f_beta": np.zeros((E,), np.float32),
            "lm_head_weight": host_init("lm_head_weight", (V, E)),
        }

        P = jax.sharding.PartitionSpec
        stack_sh = jax.sharding.NamedSharding(mesh, P(axis_name))
        rep_sh = jax.sharding.NamedSharding(mesh, P())
        self.params = {k: jax.device_put(v, stack_sh)
                       for k, v in blocks.items()}
        self.params.update({k: jax.device_put(v, rep_sh)
                            for k, v in self._rep.items()})
        self._shardings = {k: (stack_sh if k in blocks else rep_sh)
                           for k in self.params}
        self.opt_states = {
            n: tuple(jax.device_put(np.zeros_like(np.asarray(v)),
                                    self._shardings[n])
                     for _ in range(self._n_states))
            for n, v in self.params.items()}
        self._step_fn = self._build()

    # ------------------------------------------------------------ build
    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.nn import _softmax_xent_head_fn
        from ..ops.registry import OpContext, get_op
        from .mesh import shard_map_fn

        cfg = self.cfg
        axis = self.axis_name
        M = cfg["num_microbatches"]
        b = cfg["batch_size"] // M // self._ndp  # per-device microbatch
        data_axes = self._data_axes
        S, E, V = cfg["seq_len"], cfg["embed"], cfg["vocab_size"]
        heads, causal = cfg["heads"], cfg["causal"]
        attn_impl = cfg["attn_impl"]
        lowp = cfg["dtype"] in ("float16", "bfloat16")
        act_dtype = jnp.dtype(cfg["dtype"]) if lowp else jnp.float32
        sxh = _softmax_xent_head_fn(1.0, -1.0, False, "null", 0)
        block_leaves = [l for l, _, _ in _PP_BLOCK_LEAVES]

        def stage_apply(bp, x):
            # scan over this stage's local blocks
            def one(x, p):
                return _pp_block(x, p, heads, causal, attn_impl), None

            x, _ = lax.scan(one, x, bp)
            return x

        stage_apply = jax.checkpoint(stage_apply)

        def pipeline_loss(params, tokens, labels):
            # inside shard_map: block leaves are (layers/L, ...) local
            L = _axis_size(axis)
            idx = lax.axis_index(axis)
            bp = {l: params[l] for l in block_leaves}
            tok_w = params["tok_embed_weight"]
            pos_w = params["pos_embed_weight"]

            def embed(tk):
                x = tok_w[tk.astype(jnp.int32)] + pos_w[None, :, :]
                return x.astype(act_dtype)

            state = jnp.zeros((b, S, E), act_dtype)
            outs = jnp.zeros((M, b, S, E), act_dtype)
            if hasattr(lax, "pcast"):
                state = lax.pcast(state, (axis,) + data_axes,
                                  to="varying")
                outs = lax.pcast(outs, (axis,) + data_axes,
                                 to="varying")
            perm = [(i, i + 1) for i in range(L - 1)]

            def tick(carry, t):
                state, outs = carry
                x0 = embed(tokens[jnp.minimum(t, M - 1)])
                x_in = jnp.where(idx == 0, x0, state)
                y = stage_apply(bp, x_in)
                slot = t - (L - 1)
                take = (idx == L - 1) & (slot >= 0) & (slot < M)
                safe = jnp.clip(slot, 0, M - 1)
                outs = outs.at[safe].set(jnp.where(take, y, outs[safe]))
                state = lax.ppermute(y, axis, perm)
                return (state, outs), None

            (_, outs), _ = lax.scan(tick, (state, outs),
                                    jnp.arange(M + L - 1))
            # head on the LAST stage only: other stages feed zeros, so
            # their (cotangent-ignoring) fused-head dW is exactly zero
            # and the replicated head-grad psum stays correct
            z = _pp_layer_norm(outs.reshape(M * b * S, E),
                               params["ln_f_gamma"],
                               params["ln_f_beta"])
            z = jnp.where(idx == L - 1, z, jnp.zeros_like(z))
            loss_vec = sxh(z, params["lm_head_weight"],
                           labels.reshape(-1).astype(jnp.float32))
            loss = jnp.sum(jnp.where(idx == L - 1, loss_vec, 0.0))
            return lax.psum(loss, (axis,) + data_axes)

        P = jax.sharding.PartitionSpec
        spec_of = {n: (P(axis) if n in block_leaves else P())
                   for n in self.params}
        # microbatch tokens/labels (M, b, S): batch axis shards over
        # the data axes (if any); the M and S axes stay unsharded
        data_spec = P(None, data_axes if data_axes else None)
        shard_map = shard_map_fn()
        smap_kw = dict(mesh=self.mesh,
                       in_specs=({n: spec_of[n] for n in self.params},
                                 data_spec, data_spec),
                       out_specs=P())
        # replication of the replicated-param cotangents cannot be
        # statically inferred through the transpose of the tick loop —
        # disable the varying-axes check (the transpose then inserts
        # the psums itself); flag name differs across jax versions
        try:
            sharded_loss = shard_map(pipeline_loss, check_vma=False,
                                     **smap_kw)
        except TypeError:  # pragma: no cover - older jax
            sharded_loss = shard_map(pipeline_loss, check_rep=False,
                                     **smap_kw)

        opt_op = get_op(self._opt_op)
        opt_attrs = dict(self._opt_attrs)
        n_states = self._n_states
        is_adam = self._opt_op == "adam_update"
        b1, b2 = self._adam_b1, self._adam_b2

        def step(params, opt_states, lr, t, tokens, labels):
            if is_adam:
                lr = lr * jnp.sqrt(1.0 - jnp.power(b2, t)) \
                    / (1.0 - jnp.power(b1, t))
            loss, grads = jax.value_and_grad(sharded_loss)(
                params, tokens, labels)
            new_params, new_states = {}, {}
            for name, w in params.items():
                g = grads[name].astype(w.dtype)
                res, _ = opt_op.apply(
                    [w, g] + list(opt_states[name]),
                    dict(opt_attrs, lr=lr), OpContext(is_train=True))
                new_params[name] = res[0]
                new_states[name] = tuple(res[1:1 + n_states])
            return new_params, new_states, loss

        param_sh = self._shardings
        state_sh = {n: tuple(param_sh[n] for _ in range(n_states))
                    for n in self.params}
        data_sh = jax.sharding.NamedSharding(self.mesh, data_spec)
        return jax.jit(step,
                       in_shardings=(param_sh, state_sh, None, None,
                                     data_sh, data_sh),
                       out_shardings=(param_sh, state_sh, None),
                       donate_argnums=(0, 1))

    # ------------------------------------------------------------- call
    def __call__(self, batch: Dict[str, Any]):
        """One pipelined train step; returns the mean per-position loss."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        M = cfg["num_microbatches"]
        b = cfg["batch_size"] // M
        self.num_update += 1
        tokens = jnp.asarray(np.asarray(batch["data"])).reshape(
            M, b, cfg["seq_len"])
        labels = jnp.asarray(np.asarray(batch["softmax_label"])).reshape(
            M, b, cfg["seq_len"])
        self.params, self.opt_states, loss = self._step_fn(
            self.params, self.opt_states, jnp.float32(self.lr),
            jnp.float32(self.num_update), tokens, labels)
        n = cfg["batch_size"] * cfg["seq_len"]
        return float(loss) / n

    # ------------------------------------------------------------ fence
    def sync(self) -> float:
        name = min(self.params, key=lambda n: self.params[n].size)
        return float(np.asarray(self.params[name]).ravel()[0])

    # ----------------------------------------------------------- params
    def get_params(self):
        """Per-block symbol-style names (block{i}_*, tok_embed_weight,
        …) → NDArray, Module/checkpoint-compatible."""
        from ..ndarray.ndarray import NDArray

        out = {}
        for leaf, _, _ in _PP_BLOCK_LEAVES:
            stacked = np.asarray(self.params[leaf])
            for i in range(self.cfg["num_layers"]):
                out["block%d_%s" % (i, leaf)] = NDArray(stacked[i])
        for n in self._rep:
            out[n] = NDArray(np.asarray(self.params[n]))
        return out

    def set_params(self, arg_params):
        """Load per-block named params (the inverse of get_params)."""
        import jax

        def data(v):
            return np.asarray(v.data if hasattr(v, "data") else v)

        for leaf, _, _ in _PP_BLOCK_LEAVES:
            per = []
            for i in range(self.cfg["num_layers"]):
                name = "block%d_%s" % (i, leaf)
                per.append(data(arg_params[name]) if name in arg_params
                           else np.asarray(self.params[leaf])[i])
            self.params[leaf] = jax.device_put(
                np.stack(per), self._shardings[leaf])
        for n in self._rep:
            if n in arg_params:
                self.params[n] = jax.device_put(
                    data(arg_params[n]), self._shardings[n])
