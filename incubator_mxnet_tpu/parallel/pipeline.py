"""Pipeline parallelism (pp): stages laid out over a mesh axis.

The reference's only model parallelism is manual layer placement via
``group2ctx`` + ``_CrossDeviceCopy`` (``graph_executor.cc:279-393``),
demonstrated by the model-parallel LSTM example.  The TPU-native
generalization is a collective-permute pipeline: device *i* holds stage
*i*'s parameters, microbatches flow device→device over ICI via
``lax.ppermute`` inside one jitted program (GPipe schedule: M + L − 1
ticks for M microbatches through L stages), so stage compute and the
activation hop overlap the way ``_CrossDeviceCopy`` engine ops did.

All stages must share one activation shape (the classic constraint);
width changes belong inside a stage.
"""
from __future__ import annotations

import functools
from typing import Callable

__all__ = ["pipeline_apply", "pipeline_parallel_apply"]


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches,
                   axis_name: str = "pp"):
    """Run microbatches through the stage pipeline (shard_map body).

    stage_fn(params, x) -> y with ``y.shape == x.shape``; stage_params is
    the LOCAL stage's parameter pytree (sharded over ``axis_name`` by the
    caller); ``x_microbatches`` (M, ...) is replicated — device 0 injects
    microbatch t at tick t, device L−1 collects the finished microbatch
    at tick t ≥ L−1.  Returns (M, ...) outputs, replicated via a final
    psum so every stage sees the result (loss is usually computed on the
    last stage; replication keeps the API simple at toy scale).
    """
    import jax.numpy as jnp
    from jax import lax

    L = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    perm = [(i, i + 1) for i in range(L - 1)]  # no wraparound: a chain

    # the carries must be marked device-varying over the pipeline axis
    # (the loop writes per-stage values into them); fresh zeros would be
    # unvarying and rejected as a scan carry under shard_map
    state = jnp.zeros_like(x_microbatches[0])
    outs = jnp.zeros_like(x_microbatches)
    if hasattr(lax, "pcast"):
        state = lax.pcast(state, (axis_name,), to="varying")
        outs = lax.pcast(outs, (axis_name,), to="varying")

    def tick(t, carry):
        state, outs = carry
        # device 0 injects microbatch t (a dummy repeat past the end —
        # masked out downstream because its result never lands in a slot)
        inj = x_microbatches[jnp.minimum(t, M - 1)]
        x_in = jnp.where(idx == 0, inj, state)
        y = stage_fn(stage_params, x_in)
        # last device banks finished microbatch (slot = t − (L−1))
        slot = t - (L - 1)
        take = (idx == L - 1) & (slot >= 0) & (slot < M)
        safe = jnp.clip(slot, 0, M - 1)
        outs = outs.at[safe].set(jnp.where(take, y, outs[safe]))
        state = lax.ppermute(y, axis_name, perm)
        return state, outs

    _, outs = lax.fori_loop(0, M + L - 1, tick, (state, outs))
    # only the last stage holds real outputs; replicate
    return lax.psum(jnp.where(idx == L - 1, outs, 0.0), axis_name)


def pipeline_parallel_apply(mesh, stage_fn: Callable, stacked_params,
                            x_microbatches, axis_name: str = "pp"):
    """Jit-compiled pipeline over ``mesh``.

    stacked_params: pytree whose leaves have a leading stage dim (L, ...)
    — sharded one stage per device over ``axis_name``; x_microbatches
    (M, ...) replicated.

    The jitted program is cached per (mesh, stage_fn, axis_name) — pass a
    STABLE ``stage_fn`` (module-level function, not a fresh lambda per
    call) or every call retraces and recompiles.
    """
    fn = _build_pipeline(mesh, stage_fn, axis_name,
                         jax_tree_structure(stacked_params))
    return fn(stacked_params, x_microbatches)


def jax_tree_structure(tree):
    import jax

    return jax.tree.structure(tree)


@functools.lru_cache(maxsize=64)
def _build_pipeline(mesh, stage_fn, axis_name, params_treedef):
    """Cached jitted pipeline — a fresh closure per call would defeat
    jax.jit's cache and retrace/recompile every step."""
    import jax

    from .mesh import shard_map_fn

    P = jax.sharding.PartitionSpec

    def body(params, x):
        import jax.numpy as jnp

        local = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)
        return pipeline_apply(stage_fn, local, x, axis_name)

    spec_p = jax.tree.unflatten(
        params_treedef, [P(axis_name)] * params_treedef.num_leaves)
    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(spec_p, P()), out_specs=P())
    return jax.jit(fn)
