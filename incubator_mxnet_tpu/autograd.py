"""Imperative autograd.

Reference analog: ``autograd::AutogradRuntime`` (``src/ndarray/autograd.h:42-149``,
``.cc:174-279``) — thread-local ``is_train``/``is_recording`` flags, a tape of
``AGNode`` entries hung off output NDArrays, and ``ComputeGradient`` walking
the tape.  TPU-native redesign: each tape node stores the op + captured input
values; ``backward`` runs reverse topological order calling ``jax.vjp`` of the
op's forward per node — no separate Gradient graph pass or fresh executor is
needed because jax vjp *is* the gradient pass.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad",
           "set_recording", "set_training", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    s = _st()
    old, s.recording = s.recording, flag
    return old


def set_training(flag: bool) -> bool:
    s = _st()
    old, s.training = s.training, flag
    return old


class _RecordingState:
    """``with autograd.record():`` context (python/mxnet/autograd.py)."""

    def __init__(self, enter_record: Optional[bool], enter_train: Optional[bool]):
        self._er = enter_record
        self._et = enter_train
        self._old_r = None
        self._old_t = None

    def __enter__(self):
        if self._er is not None:
            self._old_r = set_recording(self._er)
        if self._et is not None:
            self._old_t = set_training(self._et)
        return self

    def __exit__(self, *exc):
        if self._old_r is not None:
            set_recording(self._old_r)
        if self._old_t is not None:
            set_training(self._old_t)


def record(train_mode: bool = True) -> _RecordingState:
    return _RecordingState(True, train_mode)


def pause(train_mode: bool = False) -> _RecordingState:
    return _RecordingState(False, train_mode)


def train_mode() -> _RecordingState:
    return _RecordingState(None, True)


def predict_mode() -> _RecordingState:
    return _RecordingState(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------


class TapeNode:
    """AGNode analog: one recorded op application."""

    __slots__ = ("op", "attrs", "opctx", "inputs", "input_vals", "n_args",
                 "out_entries", "custom", "out_info")

    def __init__(self, op, attrs, opctx, inputs, input_vals, n_args):
        self.op = op
        self.attrs = attrs
        self.opctx = opctx
        self.inputs = inputs          # list of NDArray (strong refs)
        self.input_vals = input_vals  # jax arrays captured at record time
        self.n_args = n_args          # inputs beyond this are aux (no grads)
        self.custom = None            # Function instance (custom backward)
        self.out_info = None          # [(shape, dtype)] per recorded output


def record_op(op, attrs, opctx, input_nds, input_vals, output_nds,
              n_args: int) -> None:
    """Called by the nd invoke path while recording
    (``AutogradRuntime::RecordImperativeFCompute`` analog)."""
    node = TapeNode(op, dict(attrs), opctx, list(input_nds),
                    list(input_vals), n_args)
    for i, o in enumerate(output_nds):
        o._ag_entry = (node, i)


class Function:
    """Customized differentiation (reference ``python/mxnet/autograd.py:291``).

    Subclass and override :meth:`forward` and :meth:`backward`; both run
    on NDArrays with recording paused, so anything computed inside them
    is invisible to the tape — the user-supplied ``backward`` is the
    gradient, wholesale.  Use when the true derivative is not what you
    want autograd to propagate (straight-through estimators, numerically
    stabilized forms, gradient clipping/reversal at a cut point)::

        class sigmoid(mx.autograd.Function):
            def forward(self, x):
                y = 1 / (1 + mx.nd.exp(-x))
                self.save_for_backward(y)
                return y

            def backward(self, dy):
                y, = self.saved_tensors
                return dy * y * (1 - y)

    Each instance records at most one call (state such as
    ``saved_tensors`` belongs to that call); instantiate per use.
    ``backward`` must return one gradient per ``forward`` input (or
    ``None`` to send no gradient into that input).
    """

    def __init__(self):
        self._recorded = False
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        """Stash tensors for :meth:`backward` (``self.saved_tensors``)."""
        self.saved_tensors = args

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        if self._recorded:
            raise MXNetError(
                "a Function instance records a single call; make a new "
                "%s() per application" % type(self).__name__)
        with pause(train_mode=is_training()):
            outputs = self.forward(*inputs)
        if not is_recording():
            return outputs
        self._recorded = True
        outs = outputs if isinstance(outputs, (list, tuple)) else (outputs,)
        node = TapeNode(None, {}, None, list(inputs),
                        [x.data for x in inputs], len(inputs))
        node.custom = self
        node.out_info = [(o.shape, o.data.dtype) for o in outs]
        for i, o in enumerate(outs):
            o._ag_entry = (node, i)
        return outputs


def mark_variables(variables: Sequence[Any], gradients: Sequence[Any],
                   grad_reqs="write") -> None:
    """``MXAutogradMarkVariables``: declare leaf variables with grad buffers."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._ag_entry = ("var", None)
        v.grad = g
        v._grad_req = req


def _toposort(heads) -> List[TapeNode]:
    order: List[TapeNode] = []
    seen = set()

    def visit(nd_arr):
        entry = getattr(nd_arr, "_ag_entry", None)
        if entry is None or entry[0] == "var":
            return
        node = entry[0]
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp in node.inputs[:node.n_args]:
            visit(inp)
        order.append(node)

    for h in heads:
        visit(h)
    return order


def backward(heads: Sequence[Any], head_grads: Optional[Sequence[Any]] = None,
             retain_graph: bool = False, train_mode: bool = True) -> None:
    """``MXAutogradBackward``: accumulate gradients into marked variables'
    grad buffers."""
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    order = _toposort(heads)

    # cotangent accumulator keyed by producing (node, out_idx); gradients for
    # marked leaf variables accumulate in var_accum and are committed at the
    # end per grad_req (write = overwrite previous backward; within one
    # backward all paths always sum — reference engine kAddTo semantics)
    cotan: Dict[Any, Any] = {}
    var_accum: Dict[int, Any] = {}
    var_objs: Dict[int, Any] = {}
    if head_grads is None:
        head_grads = [None] * len(heads)
    for h, hg in zip(heads, head_grads):
        g = (jnp.ones(h.shape, dtype=h.data.dtype) if hg is None
             else (hg.data if isinstance(hg, NDArray) else jnp.asarray(hg)))
        entry = getattr(h, "_ag_entry", None)
        if entry is not None and entry[0] == "var":
            var_accum[id(h)] = var_accum.get(id(h), 0) + g
            var_objs[id(h)] = h
            continue
        key = _entry_key(h)
        cotan[key] = cotan.get(key, 0) + g

    def accumulate(inp, g):
        entry = getattr(inp, "_ag_entry", None)
        if entry is None:
            return
        if entry[0] == "var":
            if inp._grad_req == "null" or inp.grad is None:
                return
            var_accum[id(inp)] = var_accum.get(id(inp), 0) + g
            var_objs[id(inp)] = inp
        else:
            key = (id(entry[0]), entry[1])
            cotan[key] = (cotan[key] + g) if key in cotan else g

    for node in reversed(order):
        nid = id(node)
        if not any(k[0] == nid for k in cotan):
            continue

        if node.custom is not None:
            # Function node: the user-supplied backward IS the vjp
            out_grads = tuple(
                NDArray(jnp.asarray(cotan[(nid, i)], dtype)
                        if (nid, i) in cotan else jnp.zeros(shape, dtype))
                for i, (shape, dtype) in enumerate(node.out_info))
            with pause():
                in_grads = node.custom.backward(*out_grads)
            if not isinstance(in_grads, (list, tuple)):
                in_grads = (in_grads,)
            if len(in_grads) != node.n_args:
                raise MXNetError(
                    "%s.backward returned %d gradient(s) for %d "
                    "forward input(s)" % (type(node.custom).__name__,
                                          len(in_grads), node.n_args))
            for inp, g in zip(node.inputs, in_grads):
                if g is not None:
                    accumulate(inp, g.data if isinstance(g, NDArray)
                               else jnp.asarray(g))
            continue

        primals = tuple(node.input_vals[:node.n_args])
        aux_vals = tuple(node.input_vals[node.n_args:])

        def fwd(*args, _node=node, _aux=aux_vals):
            outs, _ = _node.op.apply(list(args) + list(_aux), _node.attrs,
                                     _node.opctx)
            return tuple(outs)

        out_primals, vjp_fn = jax.vjp(fwd, *primals)
        # cotangent count must match the op's true output count, which only
        # the forward knows (e.g. topk ret_typ-dependent outputs)
        full_ct = tuple(
            cotan.get((nid, i), None) if cotan.get((nid, i), None) is not None
            else jnp.zeros_like(op_)
            for i, op_ in enumerate(out_primals))
        in_grads = vjp_fn(full_ct)

        for inp, g in zip(node.inputs[:node.n_args], in_grads):
            accumulate(inp, g)

    for vid, g in var_accum.items():
        v = var_objs[vid]
        if v.grad is None or v._grad_req == "null":
            continue
        if v._grad_req == "add":
            v.grad._set_data(v.grad.data + g)
        else:
            v.grad._set_data(
                (g if not hasattr(g, "astype") else
                 g.astype(v.grad.data.dtype)))


def _entry_key(nd_arr):
    entry = getattr(nd_arr, "_ag_entry", None)
    if entry is None or entry[0] == "var":
        return ("head", id(nd_arr))
    return (id(entry[0]), entry[1])


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """``autograd.grad`` — return grads instead of accumulating into buffers."""
    from .ndarray.ndarray import NDArray

    import jax.numpy as jnp

    saved = [(v.grad, v._grad_req, getattr(v, "_ag_entry", None))
             for v in variables]
    grads = [NDArray(jnp.zeros(v.shape, dtype=v.data.dtype), ctx=v._ctx)
             for v in variables]
    mark_variables(variables, grads)
    backward(heads if isinstance(heads, (list, tuple)) else [heads],
             head_grads, retain_graph=bool(retain_graph),
             train_mode=train_mode)
    for v, (g, req, entry) in zip(variables, saved):
        v.grad, v._grad_req = g, req
        if entry is not None:
            v._ag_entry = entry
    return grads
