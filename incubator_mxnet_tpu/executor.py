"""Executor — the bound, compiled form of a Symbol.

Reference analog: ``GraphExecutor`` (``src/executor/graph_executor.cc``) via
``Executor::Bind/SimpleBind`` + the python wrapper ``python/mxnet/executor.py``.

TPU-native redesign (SURVEY.md §7): instead of NNVM passes + cached engine
ops, binding lowers the symbol DAG to a jax function and compiles it with
``jax.jit``:

- PlanMemory / inplace / bulk-exec segments → XLA buffer assignment + fusion;
- the Gradient pass → ``jax.vjp`` over the lowered function;
- forward(is_train=True) is *deferred*: ``backward()`` runs ONE fused
  fwd+bwd XLA program (outputs + input grads + updated aux in a single
  compiled call), which is how the reference's dataflow engine overlapped
  forward/backward and how TPU utilization is kept high.  Accessing
  ``outputs`` before backward falls back to a forward-only program.
- BatchNorm-style aux states are functional outputs rebound after each run
  (the reference mutated aux NDArrays in place).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import random as _random
from . import telemetry
from .base import MXNetError, dtype_np
from .context import Context, current_context
from .ndarray.ndarray import NDArray
from .ops.registry import OpContext

__all__ = ["Executor"]


def _time_first_call(fn, metric: str):
    """Observe the first invocation's wall time (trace + XLA compile) into
    ``metric``; later calls go straight through.  Only installed when
    telemetry is enabled, so the disabled hot path keeps the bare jit fn."""
    state = {"fn": None}

    def wrapper(*a, **kw):
        if state["fn"] is None:
            t0 = time.perf_counter()
            out = fn(*a, **kw)
            telemetry.histogram(metric).observe(time.perf_counter() - t0)
            state["fn"] = fn
            return out
        return state["fn"](*a, **kw)

    return wrapper


class Executor:
    def __init__(self, symbol, ctx: Context, arg_dict, grad_dict,
                 grad_req: Dict[str, str], aux_dict, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self.arg_dict: Dict[str, NDArray] = arg_dict
        self.grad_dict: Dict[str, NDArray] = grad_dict
        self.aux_dict: Dict[str, NDArray] = aux_dict
        self._grad_req = grad_req
        self._group2ctx = group2ctx or {}
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._monitor_callback = None

        self._fwd_jit = {}   # is_train -> jitted forward
        self._bwd_jit = None  # combined fwd+bwd
        self._outputs_cache: Optional[List[NDArray]] = None
        self._pending_train = False
        self._aux_written = False
        self._last_key = None

    # ------------------------------------------------------------ properties
    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs_cache is None:
            self._run_forward()
        return self._outputs_cache

    # ---------------------------------------------------------------- lower
    def _is_grouped(self) -> bool:
        """True when group2ctx actually spans a device different from the
        bind context — then the symbol is partitioned into per-device
        jitted segments with explicit transfers (the reference's
        PlaceDevice + ``_CrossDeviceCopy``, ``graph_executor.cc:279-393``)
        and the top-level driver must run eagerly (jax.jit refuses
        arguments committed to different devices)."""
        if not self._group2ctx:
            return False
        devs = {ctx.jax_device for ctx in self._group2ctx.values()}
        devs.add(self._ctx.jax_device)
        return len(devs) > 1

    def _lowered(self, is_train: bool):
        """Build the jax function over (args, aux, key) once."""
        from .lowering import lower_symbol, lower_symbol_grouped

        if self._is_grouped():
            return lower_symbol_grouped(self._symbol, is_train,
                                        self._group2ctx,
                                        self._ctx.jax_device)
        return lower_symbol(self._symbol, is_train)

    def _get_fwd(self, is_train: bool):
        if is_train not in self._fwd_jit:
            import jax

            telemetry.counter("jit_compile_total").inc()
            t0 = time.perf_counter()
            fn = self._lowered(is_train)
            # grouped driver already jits per segment; the driver itself
            # must stay eager (cross-device transfers inside)
            jitted = fn if self._is_grouped() else jax.jit(fn)
            telemetry.histogram("jit_build_seconds").observe(
                time.perf_counter() - t0)
            if telemetry.enabled() and not self._is_grouped():
                jitted = _time_first_call(jitted, "jit_compile_seconds")
            self._fwd_jit[is_train] = jitted
        return self._fwd_jit[is_train]

    def _get_bwd(self):
        if self._bwd_jit is None:
            import jax

            telemetry.counter("jit_compile_total").inc()
            t0 = time.perf_counter()
            core = self._lowered(True)
            diff_names = [n for n in self._arg_names
                          if self._grad_req.get(n, "null") != "null"]

            def bwd(arg_vals, aux_vals, key, out_grads):
                diff = {n: arg_vals[n] for n in diff_names}
                rest = {n: v for n, v in arg_vals.items()
                        if n not in diff}

                def f(d):
                    merged = dict(rest)
                    merged.update(d)
                    outs, new_aux = core(merged, aux_vals, key)
                    return outs, new_aux

                (outs, new_aux), vjp_fn = jax.vjp(f, diff)
                import jax.numpy as jnp

                ct_outs = [og if og is not None else jnp.ones_like(o)
                           for og, o in zip(out_grads, outs)]
                ct_aux = {k: jnp.zeros_like(v) for k, v in new_aux.items()}
                (grads,) = vjp_fn((ct_outs, ct_aux))
                return outs, new_aux, grads

            jitted = bwd if self._is_grouped() else jax.jit(bwd)
            telemetry.histogram("jit_build_seconds").observe(
                time.perf_counter() - t0)
            if telemetry.enabled() and not self._is_grouped():
                jitted = _time_first_call(jitted, "jit_compile_seconds")
            self._bwd_jit = jitted
        return self._bwd_jit

    # ----------------------------------------------------------------- run
    def forward(self, is_train: bool = False, **kwargs):
        """Copy kwargs into bound buffers, then run — or, for training,
        DEFER: backward() executes one fused fwd+bwd XLA program (outputs +
        grads + aux in a single compiled call; no forward recompute).
        Accessing ``outputs`` before backward falls back to a forward-only
        program.  Inference runs eagerly and returns the outputs."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %s" % k)
            self._write_buf(self.arg_dict[k], v)
        self._outputs_cache = None
        self._pending_train = is_train
        self._aux_written = False
        self._last_key = _random.next_key()
        if not is_train:
            self._run_forward()
            return self.outputs
        return None

    def _current_vals(self):
        args = {n: self.arg_dict[n].data for n in self._arg_names}
        aux = {n: self.aux_dict[n].data for n in self._aux_names}
        return args, aux

    def _run_forward(self):
        fwd = self._get_fwd(self._pending_train)
        args, aux = self._current_vals()
        key = self._last_key if self._last_key is not None \
            else _random.next_key()
        outs, new_aux = fwd(args, aux, key)
        self._set_outputs(outs)
        if self._pending_train and not self._aux_written:
            self._write_aux(new_aux)
            self._aux_written = True
        if self._monitor_callback is not None:
            self._run_monitor()

    def backward(self, out_grads=None, is_train: bool = True) -> None:
        """Fused fwd+bwd XLA program; fills grad arrays per grad_req."""
        if out_grads is None:
            ogs = [None] * len(self._output_names)
        elif isinstance(out_grads, NDArray):
            ogs = [out_grads.data]
        else:
            ogs = [g.data if isinstance(g, NDArray) else g for g in out_grads]
        bwd = self._get_bwd()
        args, aux = self._current_vals()
        key = self._last_key if self._last_key is not None \
            else _random.next_key()
        outs, new_aux, grads = bwd(args, aux, key, ogs)
        if self._outputs_cache is None:
            self._set_outputs(outs)
        # aux updates exactly once per step: skip if a forward-only run
        # already wrote them (then grads here are unaffected — train-mode
        # BN uses batch stats, not the moving aux)
        if not self._aux_written:
            self._write_aux(new_aux)
            self._aux_written = True
        for name, g in grads.items():
            req = self._grad_req.get(name, "null")
            tgt = self.grad_dict.get(name)
            if tgt is None or req == "null":
                continue
            if req == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g.astype(tgt.dtype))

    def _set_outputs(self, outs):
        self._outputs_cache = [NDArray(o, ctx=self._ctx) for o in outs]

    def _write_aux(self, new_aux):
        for n, v in new_aux.items():
            self.aux_dict[n]._set_data(v)

    # ------------------------------------------------------------- utilities
    def _write_buf(self, target: NDArray, value) -> None:
        """Copy into a bound buffer, pinned to this executor's device
        (the reference's CopyFromTo engine op with a cross-device path)."""
        import jax
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            val = value.data
        elif isinstance(value, jax.Array):
            val = value
        else:
            val = jnp.asarray(np.asarray(value))
        target._set_data(jax.device_put(val.astype(target.dtype),
                                        self._ctx.jax_device))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params: bool = False) -> None:
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self._write_buf(self.arg_dict[k], v)
            elif not allow_extra_params:
                raise MXNetError("unknown arg param %s" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self._write_buf(self.aux_dict[k], v)
            elif not allow_extra_params:
                raise MXNetError("unknown aux param %s" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new shapes sharing parameter arrays (bucketing
        support — reference ``Executor::Reshape``)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for n, s in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(s):
                new_args[n] = cur
            else:
                import jax.numpy as jnp

                new_args[n] = NDArray(jnp.zeros(s, dtype=cur.dtype),
                                      ctx=self._ctx)
        grad_dict = {}
        for n, g in self.grad_dict.items():
            s = arg_shapes[self._arg_names.index(n)]
            import jax.numpy as jnp

            grad_dict[n] = NDArray(jnp.zeros(s, dtype=g.dtype),
                                   ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args, grad_dict,
                        dict(self._grad_req), dict(self.aux_dict),
                        group2ctx=self._group2ctx)

    def set_monitor_callback(self, callback) -> None:
        self._monitor_callback = callback

    def _run_monitor(self):
        """Per-output monitor hook (``graph_executor.cc:1209-1229`` executor
        monitor; full per-internal coverage via get_internals binding)."""
        for name, arr in zip(self._output_names, self._outputs_cache):
            self._monitor_callback(name, arr)

    def debug_str(self) -> str:
        lines = ["Symbol outputs: %s" % ", ".join(self._output_names)]
        for n in self._symbol.topo_nodes():
            kind = "var" if n.is_variable else n.op.name
            lines.append("  %s %s <- %s" % (kind, n.name,
                                            [i.name for i, _ in n.inputs]))
        return "\n".join(lines)

    # ----------------------------------------------------------- construction
    @staticmethod
    def _alloc(shape, dtype, ctx: Context) -> NDArray:
        # THE host-create + single-put path (ndarray.zeros): on-device
        # creation would compile per shape and drag the buffer through
        # the ~5 MB/s D2H tunnel for any non-default ctx (measured:
        # 88 s to bind ResNet-50 with cpu-ctx executors)
        from .ndarray import zeros as nd_zeros

        return nd_zeros(shape, ctx=ctx, dtype=dtype)

    @classmethod
    def _simple_bind(cls, symbol, ctx, grad_req, type_dict, group2ctx,
                     shared_exec, shapes: Dict[str, Sequence[int]]):
        """``Symbol.simple_bind``: infer all shapes from given input shapes,
        allocate args/grads/aux (``GraphExecutor::Init`` +
        ``InitArguments``, graph_executor.cc:787,898)."""
        ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        type_dict = type_dict or {}

        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, dict):
            req = {n: grad_req.get(n, "null") for n in arg_names}
        else:
            req = dict(zip(arg_names, grad_req))
        # data inputs never get grads by default in simple_bind... the
        # reference gives every arg a grad under 'write'; match that.

        arg_dict, grad_dict, aux_dict = {}, {}, {}
        for n, s in zip(arg_names, arg_shapes):
            dt = dtype_np(type_dict.get(n, np.float32))
            if shared_exec is not None and n in shared_exec.arg_dict and \
                    tuple(shared_exec.arg_dict[n].shape) == tuple(s):
                arg_dict[n] = shared_exec.arg_dict[n]
            else:
                arg_dict[n] = cls._alloc(s, dt, ctx)
            if req[n] != "null":
                if shared_exec is not None and \
                        n in shared_exec.grad_dict and \
                        tuple(shared_exec.grad_dict[n].shape) == tuple(s):
                    grad_dict[n] = shared_exec.grad_dict[n]
                else:
                    grad_dict[n] = cls._alloc(s, dt, ctx)
        for n, s in zip(aux_names, aux_shapes):
            if shared_exec is not None and n in shared_exec.aux_dict:
                aux_dict[n] = shared_exec.aux_dict[n]
            else:
                aux_dict[n] = cls._alloc(s, np.float32, ctx)
        return cls(symbol, ctx, arg_dict, grad_dict, req, aux_dict,
                   group2ctx)

    @classmethod
    def _bind(cls, symbol, ctx, args, args_grad, grad_req, aux_states,
              group2ctx, shared_exec):
        """``Symbol.bind`` with user-provided buffers
        (``MXExecutorBindEX``)."""
        from .ndarray import array as nd_array

        ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        def to_nd(v):
            return v if isinstance(v, NDArray) else nd_array(v, ctx=ctx)

        if args is None:
            raise MXNetError("bind requires args")
        if isinstance(args, dict):
            arg_dict = {n: to_nd(args[n]) for n in arg_names if n in args}
            missing = [n for n in arg_names if n not in args]
            if missing:
                raise MXNetError("bind missing args %s" % missing)
        else:
            arg_dict = {n: to_nd(a) for n, a in zip(arg_names, args)}

        if args_grad is None:
            grad_dict = {}
        elif isinstance(args_grad, dict):
            grad_dict = {n: to_nd(g) for n, g in args_grad.items()}
        else:
            grad_dict = {n: to_nd(g)
                         for n, g in zip(arg_names, args_grad)
                         if g is not None}

        if isinstance(grad_req, str):
            req = {n: (grad_req if n in grad_dict or args_grad is None
                       else "null") for n in arg_names}
            if args_grad is None:
                req = {n: "null" for n in arg_names}
        elif isinstance(grad_req, dict):
            req = {n: grad_req.get(n, "null") for n in arg_names}
        else:
            req = dict(zip(arg_names, grad_req))

        if aux_states is None:
            aux_dict = {}
            for n in aux_names:
                raise MXNetError("bind missing aux state %s" % n)
        elif isinstance(aux_states, dict):
            aux_dict = {n: to_nd(aux_states[n]) for n in aux_names}
        else:
            aux_dict = {n: to_nd(a) for n, a in zip(aux_names, aux_states)}
        return cls(symbol, ctx, arg_dict, grad_dict, req, aux_dict,
                   group2ctx)
