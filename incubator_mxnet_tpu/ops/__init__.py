"""Operator implementations (TPU-native analog of ``src/operator/``).

Every op is registered once in :mod:`.registry` with a pure, jax-traceable
forward function plus shape-inference metadata; the imperative ``mx.nd``
namespace and the symbolic ``mx.sym`` namespace are both auto-generated from
this single registry — the analog of the reference's NNVM op registry that
feeds both ``MXImperativeInvoke`` and the symbolic executor
(``src/c_api/c_api_ndarray.cc:423``, SURVEY.md §2.3).

Gradients come from ``jax.vjp`` over the forward function instead of
hand-written ``FGradient`` registrations — exceptions (e.g. ``SoftmaxOutput``)
use ``jax.custom_vjp`` where the reference's backward is *not* the true
derivative.
"""
from . import registry  # noqa: F401
from .registry import OpDef, register, get_op, list_ops  # noqa: F401

# Import op groups for registration side effects.
from . import elemwise  # noqa: F401
from . import broadcast_reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_ops  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import ordering  # noqa: F401
from . import control_flow  # noqa: F401
from . import sequence  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import linalg  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import contrib_vision  # noqa: F401
from . import detection  # noqa: F401
