"""Elementwise unary/binary/scalar ops.

Reference analog: ``src/operator/tensor/elemwise_*`` + the ~248 scalar
functors of ``src/operator/mshadow_op.h`` (SURVEY.md §2.3).  Here each functor
is a jnp expression; XLA fuses chains of these into single kernels, which is
the TPU-native replacement for mshadow expression templates.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, parse_float

__all__ = []


def _unary(name, jfn, aliases=()):
    @register(name, arg_names=["data"], aliases=aliases,
              doc="elementwise %s (mshadow_op.h functor analog)" % name)
    def _f(ins, attrs, ctx, _j=jfn):
        return _j(ins[0])
    return _f


def _binary(name, jfn, aliases=()):
    @register(name, arg_names=["lhs", "rhs"], aliases=aliases,
              doc="elementwise binary %s" % name)
    def _f(ins, attrs, ctx, _j=jfn):
        return _j(ins[0], ins[1])
    return _f


def _binary_scalar(name, jfn, aliases=()):
    @register(name, arg_names=["data"], aliases=aliases,
              doc="binary-with-scalar %s" % name)
    def _f(ins, attrs, ctx, _j=jfn):
        s = parse_float(attrs.get("scalar", 0.0))
        x = ins[0]
        # keep integer arrays integer for whole-number scalars (reference
        # semantics: output dtype follows the array operand)
        int_in = jnp.issubdtype(x.dtype, jnp.integer) \
            and float(s).is_integer()
        if int_in:
            s = jnp.asarray(int(s), dtype=x.dtype)
        else:
            s = jnp.asarray(s, dtype=x.dtype) \
                if jnp.issubdtype(x.dtype, jnp.floating) else s
        out = _j(x, s)
        if int_in and out.dtype != x.dtype:
            # jnp true-division (and hypot) promote ints to float; the
            # reference's mshadow kernels keep the array dtype (C
            # truncation semantics)
            out = out.astype(x.dtype)
        return out
    return _f


# -- unary math -------------------------------------------------------------
_unary("negative", lambda x: -x, aliases=["_np_negative"])
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("relu", jax.nn.relu)
_unary("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)))
_unary("gammaln", jax.lax.lgamma)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))
_unary("ones_like", jnp.ones_like)
_unary("zeros_like", jnp.zeros_like)
@register("make_loss", arg_names=["data"], aliases=["MakeLoss"])
def _make_loss(ins, attrs, ctx):
    """Loss head: forward identity; backward emits
    ``grad_scale / norm`` regardless of the incoming gradient, where norm is
    1 (null), batch size (batch), or #elements > valid_thresh (valid) —
    ``src/operator/make_loss-inl.h:91-118``."""
    grad_scale = parse_float(attrs.get("grad_scale", 1.0))
    normalization = attrs.get("normalization", "null")
    valid_thresh = parse_float(attrs.get("valid_thresh", 0.0))

    @jax.custom_vjp
    def f(x):
        return x

    def f_fwd(x):
        return x, x

    def f_bwd(x, g):
        if normalization == "batch":
            norm = jnp.asarray(float(x.shape[0]), x.dtype)
        elif normalization == "valid":
            norm = jnp.maximum(
                jnp.sum((x > valid_thresh).astype(x.dtype)), 1.0)
        else:
            norm = jnp.asarray(1.0, x.dtype)
        return (jnp.full(x.shape, grad_scale, x.dtype) / norm,)

    f.defvjp(f_fwd, f_bwd)
    return f(ins[0])


_unary("BlockGrad", jax.lax.stop_gradient, aliases=["stop_gradient"])
_unary("identity", lambda x: x, aliases=["_copy"])


@functools.lru_cache(maxsize=None)
def _kl_sparse_fn(target, penalty):
    @jax.custom_vjp
    def f(x, avg):
        return x

    def f_fwd(x, avg):
        return x, avg

    def f_bwd(avg, g):
        # d/da KL(t ‖ a) = −t/a + (1−t)/(1−a), broadcast over the batch
        # rows; avg is the stored statistic, a constant w.r.t. x
        pen = penalty * (-target / avg + (1.0 - target) / (1.0 - avg))
        return g + pen[None].astype(g.dtype), jnp.zeros_like(avg)

    f.defvjp(f_fwd, f_bwd)
    return f


def _kl_sparse_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], [None]
    return [data_s], [data_s], [tuple(data_s[1:])]


@register("IdentityAttachKLSparseReg", arg_names=["data"],
          aux_names=["moving_avg"], infer_shape=_kl_sparse_infer_shape)
def _identity_attach_kl_sparse_reg(ins, attrs, ctx):
    """Identity forward with a KL sparseness penalty attached to the
    gradient (``src/operator/identity_attach_KL_sparse_reg-inl.h``).

    Inputs are sigmoid activations in (0, 1); the aux ``moving_avg``
    tracks the per-unit batch mean activation with ``momentum``, and
    the backward adds ``penalty * d/da KL(sparseness_target ‖ avg)``
    to every row — with momentum=0 this is the exact gradient of
    ``penalty * B * Σ_j KL(t ‖ colmean_j(x))``.  Attrs (reference
    defaults): sparseness_target=0.1, penalty=0.001, momentum=0.9.
    """
    data, moving_avg = ins
    target = parse_float(attrs.get("sparseness_target", 0.1))
    penalty = parse_float(attrs.get("penalty", 0.001))
    momentum = parse_float(attrs.get("momentum", 0.9))
    if ctx.is_train:
        batch_mean = jnp.mean(data.astype(moving_avg.dtype), axis=0)
        new_avg = moving_avg * momentum + batch_mean * (1.0 - momentum)
    else:
        new_avg = moving_avg
    new_avg = jax.lax.stop_gradient(new_avg)
    out = _kl_sparse_fn(target, penalty)(data, new_avg)
    return (out,), (new_avg,)


@register("Cast", arg_names=["data"], aliases=["cast"])
def _cast(ins, attrs, ctx):
    from ..base import dtype_np

    return ins[0].astype(dtype_np(attrs.get("dtype", "float32")))


@register("clip", arg_names=["data"])
def _clip(ins, attrs, ctx):
    a_min = parse_float(attrs.get("a_min"))
    a_max = parse_float(attrs.get("a_max"))
    return jnp.clip(ins[0], a_min, a_max)


def _exact_div(x, s):
    """True division for floats; exact C truncating division for int
    operands (jnp.divide promotes ints to float32, which corrupts exact
    quotients at |v| >= 2^24 — mshadow divides in the integer domain)."""
    if jnp.issubdtype(jnp.result_type(x), jnp.integer) and \
            jnp.issubdtype(jnp.result_type(s), jnp.integer):
        return jax.lax.div(jnp.asarray(x), jnp.asarray(s))
    return jnp.divide(x, s)


# -- binary (same-shape in the reference; we broadcast like the broadcast_*
#    variants so both namespaces share one kernel) --------------------------
_binary("elemwise_add", jnp.add, aliases=["_plus", "_add", "broadcast_add",
                                          "broadcast_plus"])
_binary("elemwise_sub", jnp.subtract, aliases=["_minus", "_sub",
                                               "broadcast_sub",
                                               "broadcast_minus"])
_binary("elemwise_mul", jnp.multiply, aliases=["_mul", "broadcast_mul"])
_binary("elemwise_div", _exact_div, aliases=["_div", "broadcast_div"])
_binary("_mod", jnp.mod, aliases=["broadcast_mod"])
_binary("_power", jnp.power, aliases=["_pow", "broadcast_power"])
_binary("_maximum", jnp.maximum, aliases=["broadcast_maximum"])
_binary("_minimum", jnp.minimum, aliases=["broadcast_minimum"])
_binary("_hypot", jnp.hypot, aliases=["broadcast_hypot"])
_binary("_equal", lambda a, b: (a == b).astype(jnp.result_type(a)),
        aliases=["broadcast_equal"])
_binary("_not_equal", lambda a, b: (a != b).astype(jnp.result_type(a)),
        aliases=["broadcast_not_equal"])
_binary("_greater", lambda a, b: (a > b).astype(jnp.result_type(a)),
        aliases=["broadcast_greater"])
_binary("_greater_equal", lambda a, b: (a >= b).astype(jnp.result_type(a)),
        aliases=["broadcast_greater_equal"])
_binary("_lesser", lambda a, b: (a < b).astype(jnp.result_type(a)),
        aliases=["broadcast_lesser"])
_binary("_lesser_equal", lambda a, b: (a <= b).astype(jnp.result_type(a)),
        aliases=["broadcast_lesser_equal"])
_binary("_logical_and", lambda a, b: ((a != 0) & (b != 0)).astype(jnp.result_type(a)),
        aliases=["broadcast_logical_and"])
_binary("_logical_or", lambda a, b: ((a != 0) | (b != 0)).astype(jnp.result_type(a)),
        aliases=["broadcast_logical_or"])
_binary("_logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype(jnp.result_type(a)),
        aliases=["broadcast_logical_xor"])


# -- binary with scalar -----------------------------------------------------
_binary_scalar("_plus_scalar", jnp.add)
_binary_scalar("_minus_scalar", jnp.subtract)
_binary_scalar("_rminus_scalar", lambda x, s: s - x)
_binary_scalar("_mul_scalar", jnp.multiply)
_binary_scalar("_div_scalar", _exact_div)
_binary_scalar("_rdiv_scalar", lambda x, s: _exact_div(s, x))
_binary_scalar("_mod_scalar", jnp.mod)
_binary_scalar("_rmod_scalar", lambda x, s: jnp.mod(s, x))
_binary_scalar("_power_scalar", jnp.power)
_binary_scalar("_rpower_scalar", lambda x, s: jnp.power(s, x))
_binary_scalar("_maximum_scalar", jnp.maximum)
_binary_scalar("_minimum_scalar", jnp.minimum)
_binary_scalar("_hypot_scalar", jnp.hypot)
_binary_scalar("_equal_scalar", lambda x, s: (x == s).astype(x.dtype))
_binary_scalar("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype))
_binary_scalar("_greater_scalar", lambda x, s: (x > s).astype(x.dtype))
_binary_scalar("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype))
_binary_scalar("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype))
_binary_scalar("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype))


@register("smooth_l1", arg_names=["data"])
def _smooth_l1(ins, attrs, ctx):
    sigma = parse_float(attrs.get("scalar", 1.0))
    x = ins[0]
    s2 = sigma * sigma
    return jnp.where(jnp.abs(x) < 1.0 / s2,
                     0.5 * s2 * x * x,
                     jnp.abs(x) - 0.5 / s2)


@register("add_n", arg_names=None, aliases=["ElementWiseSum", "_sum"])
def _add_n(ins, attrs, ctx):
    """n-ary sum (``src/operator/tensor/elemwise_sum.cc``)."""
    out = ins[0]
    for x in ins[1:]:
        out = out + x
    return out
