"""Shared MoE token-routing bookkeeping.

ONE implementation of the GShard top-k/capacity/dispatch math, used by
both MoE faces — the shard_map-local ``parallel/moe.py`` (explicit
``lax.all_to_all`` over an ``ep`` axis) and the global/pjit
``_contrib_MoEFFN`` op (``ops/contrib_ops.py``, XLA SPMD partitioning)
— so routing changes (priority order, capacity formula, renorm
epsilon) can never silently diverge between the twins.

Deliberately import-neutral: no ``parallel`` imports (the op registry
loads at package init and must not pull the distribution layer).
"""
from __future__ import annotations

__all__ = ["route", "sparse_dispatch", "sparse_combine"]


def route(probs, top_k: int, cap: int):
    """Top-k routing with GShard token-major capacity priority.

    ``probs`` (T, E) router probabilities.  Returns ``(gate_vals,
    flat_e, onehot, keep, safe_pos)``: renormalized gates (T, k) when
    k>1 (raw Switch gate at k=1); flat expert ids (T·k,); the f32
    one-hot (T·k, E) — kept for the PRE-capacity aux-loss counting;
    the capacity mask; and clamped buffer positions.  Positions come
    from an int32 cumsum — float32 stops representing consecutive
    integers past 2^24 assignments and would silently collide slots.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    E = probs.shape[-1]
    gate_vals, experts = lax.top_k(probs, top_k)
    if top_k > 1:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)
    flat_e = experts.reshape(-1)
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.float32)
    oh_i = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    pos = jnp.sum(oh_i * (jnp.cumsum(oh_i, axis=0) - 1), axis=-1)
    keep = pos < cap
    safe_pos = jnp.where(keep, pos, 0)
    return gate_vals, flat_e, onehot, keep, safe_pos


def sparse_dispatch(xf, flat_e, keep, safe_pos, E: int, cap: int,
                    top_k: int):
    """Fill the (E, C, d) capacity buffer — no dense (E, T, d) product;
    memory/traffic is capacity-bound.

    Two-step slot fill instead of scattering token VECTORS: (e, pos)
    pairs are unique for kept assignments (cumsum positions; top_k
    experts per token are distinct), so a d-row scatter-add was always
    collision-free — equivalently, scatter only the int32 source-token
    id per slot (tiny) and GATHER the rows, which the TPU lowers to an
    embedding-style vectorized gather rather than a serialized vector
    scatter (measured +5.5% tokens/s on the §8e MoE transformer).
    """
    import jax.numpy as jnp

    d = xf.shape[-1]
    n = flat_e.shape[0]                      # T * top_k assignments
    tok_idx = jnp.arange(n, dtype=jnp.int32) // top_k
    slot = flat_e.astype(jnp.int32) * cap + safe_pos.astype(jnp.int32)
    # 0 marks an empty slot; kept assignments write token id + 1
    src = jnp.zeros((E * cap,), jnp.int32).at[slot].max(
        jnp.where(keep, tok_idx + 1, 0))
    rows = xf[jnp.maximum(src - 1, 0)]
    buf = jnp.where((src > 0)[:, None], rows,
                    jnp.zeros((1, d), xf.dtype))
    return buf.reshape(E, cap, d)


def sparse_combine(back, flat_e, keep, safe_pos, gate_vals, top_k: int):
    """Gather each kept assignment's expert output slot and gate-sum
    over the k assignments per token.  ``back`` (E, C, d)."""
    import jax.numpy as jnp

    d = back.shape[-1]
    out_flat = back[flat_e, safe_pos]                       # (T*k, d)
    wgt = keep.astype(back.dtype) \
        * gate_vals.reshape(-1).astype(back.dtype)
    out = (out_flat * wgt[:, None])
    return out.reshape(-1, top_k, d).sum(axis=1)            # (T, d)
