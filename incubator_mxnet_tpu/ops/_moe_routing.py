"""Shared MoE token-routing bookkeeping.

ONE implementation of the GShard top-k/capacity/dispatch math, used by
both MoE faces — the shard_map-local ``parallel/moe.py`` (explicit
``lax.all_to_all`` over an ``ep`` axis) and the global/pjit
``_contrib_MoEFFN`` op (``ops/contrib_ops.py``, XLA SPMD partitioning)
— so routing changes (priority order, capacity formula, renorm
epsilon) can never silently diverge between the twins.

Deliberately import-neutral: no ``parallel`` imports (the op registry
loads at package init and must not pull the distribution layer).
"""
from __future__ import annotations

__all__ = ["route", "sparse_dispatch", "sparse_combine"]


def route(probs, top_k: int, cap: int):
    """Top-k routing with GShard token-major capacity priority.

    ``probs`` (T, E) router probabilities.  Returns ``(gate_vals,
    flat_e, onehot, keep, safe_pos)``: renormalized gates (T, k) when
    k>1 (raw Switch gate at k=1); flat expert ids (T·k,); the f32
    one-hot (T·k, E) — kept for the PRE-capacity aux-loss counting;
    the capacity mask; and clamped buffer positions.  Positions come
    from an int32 cumsum — float32 stops representing consecutive
    integers past 2^24 assignments and would silently collide slots.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    E = probs.shape[-1]
    gate_vals, experts = lax.top_k(probs, top_k)
    if top_k > 1:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)
    flat_e = experts.reshape(-1)
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.float32)
    oh_i = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    pos = jnp.sum(oh_i * (jnp.cumsum(oh_i, axis=0) - 1), axis=-1)
    keep = pos < cap
    safe_pos = jnp.where(keep, pos, 0)
    return gate_vals, flat_e, onehot, keep, safe_pos


def sparse_dispatch(xf, flat_e, keep, safe_pos, E: int, cap: int,
                    top_k: int):
    """Scatter tokens into the (E, C, d) capacity buffer — no dense
    (E, T, d) product; memory/traffic is capacity-bound."""
    import jax.numpy as jnp

    T = xf.shape[0]
    d = xf.shape[-1]
    tok_idx = jnp.arange(T * top_k) // top_k
    contrib = jnp.where(keep[:, None], xf[tok_idx],
                        jnp.zeros((1, d), xf.dtype))
    return jnp.zeros((E, cap, d), xf.dtype).at[
        flat_e, safe_pos].add(contrib)


def sparse_combine(back, flat_e, keep, safe_pos, gate_vals, top_k: int):
    """Gather each kept assignment's expert output slot and gate-sum
    over the k assignments per token.  ``back`` (E, C, d)."""
    import jax.numpy as jnp

    d = back.shape[-1]
    out_flat = back[flat_e, safe_pos]                       # (T*k, d)
    wgt = keep.astype(back.dtype) \
        * gate_vals.reshape(-1).astype(back.dtype)
    out = (out_flat * wgt[:, None])
    return out.reshape(-1, top_k, d).sum(axis=1)            # (T, d)
