"""Sequence ops (``src/operator/sequence_last/mask/reverse-inl.h``).

Layout follows the reference: time-major ``(seq_len, batch, ...)`` with an
optional per-batch ``sequence_length`` vector.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, parse_bool, parse_float

__all__ = []


@register("SequenceLast", arg_names=["data", "sequence_length"])
def _seq_last(ins, attrs, ctx):
    data = ins[0]
    use_len = parse_bool(attrs.get("use_sequence_length", False))
    if not use_len or len(ins) < 2 or ins[1] is None:
        return data[-1]
    seq_len = ins[1].astype(jnp.int32)
    idx = jnp.clip(seq_len - 1, 0, data.shape[0] - 1)
    batch = jnp.arange(data.shape[1])
    return data[idx, batch]


@register("SequenceMask", arg_names=["data", "sequence_length"])
def _seq_mask(ins, attrs, ctx):
    data = ins[0]
    use_len = parse_bool(attrs.get("use_sequence_length", False))
    value = parse_float(attrs.get("value", 0.0))
    if not use_len or len(ins) < 2 or ins[1] is None:
        return data
    seq_len = ins[1].astype(jnp.int32)
    t = jnp.arange(data.shape[0])[:, None]
    mask = t < seq_len[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceReverse", arg_names=["data", "sequence_length"])
def _seq_reverse(ins, attrs, ctx):
    data = ins[0]
    use_len = parse_bool(attrs.get("use_sequence_length", False))
    if not use_len or len(ins) < 2 or ins[1] is None:
        return jnp.flip(data, axis=0)
    seq_len = ins[1].astype(jnp.int32)
    T = data.shape[0]
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < seq_len[None, :], seq_len[None, :] - 1 - t, t)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[src, batch]
