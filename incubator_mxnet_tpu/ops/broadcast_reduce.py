"""Reduction + broadcast ops.

Reference analog: ``src/operator/tensor/broadcast_reduce_op*`` with its custom
CUDA kernels (``broadcast_reduce-inl.cuh``).  On TPU these lower to XLA
``reduce``/``broadcast_in_dim`` which tile natively onto the VPU — no custom
kernels required (SURVEY.md §7 "What NOT to rebuild").
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, parse_tuple, parse_bool, parse_int

__all__ = []


def _norm_axis(axis, ndim):
    if axis is None or axis == () or axis == "":
        return None
    if isinstance(axis, (int,)):
        axis = (axis,)
    axis = parse_tuple(axis)
    return tuple(a % ndim for a in axis)


def _reduce(name, jfn, aliases=()):
    @register(name, arg_names=["data"], aliases=aliases,
              doc="reduction %s over `axis` with keepdims/exclude" % name)
    def _f(ins, attrs, ctx, _j=jfn):
        x = ins[0]
        axis = _norm_axis(attrs.get("axis"), x.ndim)
        if parse_bool(attrs.get("exclude", False)) and axis is not None:
            axis = tuple(i for i in range(x.ndim) if i not in axis)
        keepdims = parse_bool(attrs.get("keepdims", False))
        return _j(x, axis=axis, keepdims=keepdims)
    return _f


_reduce("sum", jnp.sum, aliases=["sum_axis"])
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("max", jnp.max, aliases=["max_axis"])
_reduce("min", jnp.min, aliases=["min_axis"])
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)


@register("norm", arg_names=["data"])
def _norm(ins, attrs, ctx):
    x = ins[0]
    ord_ = parse_int(attrs.get("ord"), 2)
    axis = _norm_axis(attrs.get("axis"), x.ndim)
    keepdims = parse_bool(attrs.get("keepdims", False))
    if ord_ == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


def _arg_reduce(name, jfn):
    @register(name, arg_names=["data"])
    def _f(ins, attrs, ctx, _j=jfn):
        x = ins[0]
        axis = attrs.get("axis")
        keepdims = parse_bool(attrs.get("keepdims", False))
        if axis is None or axis == "" :
            # reference argmax default flattens
            out = _j(x.reshape(-1), axis=0)
            return out.astype(jnp.float32)
        axis = parse_int(axis)
        out = _j(x, axis=axis)
        if keepdims:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.float32)
    return _f


_arg_reduce("argmax", jnp.argmax)
_arg_reduce("argmin", jnp.argmin)


@register("argmax_channel", arg_names=["data"])
def _argmax_channel(ins, attrs, ctx):
    return jnp.argmax(ins[0], axis=1).astype(jnp.float32)


@register("broadcast_to", arg_names=["data"])
def _broadcast_to(ins, attrs, ctx):
    x = ins[0]
    shape = parse_tuple(attrs.get("shape"))
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", arg_names=["data"], aliases=["broadcast_axes"])
def _broadcast_axis(ins, attrs, ctx):
    x = ins[0]
    axes = parse_tuple(attrs.get("axis"))
    sizes = parse_tuple(attrs.get("size"))
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_like", arg_names=["lhs", "rhs"])
def _broadcast_like(ins, attrs, ctx):
    return jnp.broadcast_to(ins[0], ins[1].shape)


@register("reshape_like", arg_names=["lhs", "rhs"])
def _reshape_like(ins, attrs, ctx):
    return ins[0].reshape(ins[1].shape)
