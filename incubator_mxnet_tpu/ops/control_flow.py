"""Control-flow-adjacent ops (where lives in indexing.py; this module hosts
the functional control-flow entry points used by RNN fusion: the TPU-native
replacement for per-timestep graph unrolling is ``lax.scan``)."""
from __future__ import annotations

import jax

__all__ = ["scan", "cond", "while_loop"]

scan = jax.lax.scan
cond = jax.lax.cond
while_loop = jax.lax.while_loop
