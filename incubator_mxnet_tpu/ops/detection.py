"""Detection operators: SSD MultiBox family, ROIPooling, Faster-RCNN Proposal.

Reference analogs (semantics matched, implementation redesigned for XLA):

- ``_contrib_MultiBoxPrior``   — ``src/operator/contrib/multibox_prior.cc``
- ``_contrib_MultiBoxTarget``  — ``src/operator/contrib/multibox_target.cc``
- ``_contrib_MultiBoxDetection`` — ``src/operator/contrib/multibox_detection.cc``
- ``ROIPooling``               — ``src/operator/roi_pooling.cc:39``
- ``_contrib_Proposal``        — ``src/operator/contrib/proposal.cc:280``

TPU-first design notes.  The reference kernels are sequential CPU loops
(greedy bipartite matching, O(n^2) NMS with early exit, compaction of valid
detections).  None of that control flow survives under XLA's static-shape
model, so every op here is re-expressed as fixed-trip-count tensor programs:

- bipartite matching = ``lax.fori_loop`` over at most ``num_labels`` rounds,
  each round a masked global argmax over the (anchors, labels) IoU matrix —
  identical greedy semantics, fully vectorized per round;
- NMS = full suppression matrix built by a ``fori_loop`` whose body is a
  vectorized IoU row; the reference's "stop after post_nms_top_n kept" early
  exit is equivalent to running suppression to completion and slicing the
  first k survivors (later boxes can only suppress boxes that are also past
  the cut), so the padded-shape program returns bit-identical keeps;
- "compaction" (moving valid rows to the front) = a stable argsort on a
  validity key, which XLA lowers to one sort;
- ROI pooling = a masked max over the feature map per output bin (gradient
  flows to the argmax via jax autodiff — no explicit ``max_idx`` aux needed).

Everything is jit-compatible and batchable with ``jax.vmap``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import (register, parse_float, parse_int, parse_tuple,
                       parse_bool)

__all__ = []

_NEG = -1e30


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _iou_matrix(a, b):
    """Corner-format IoU between (N,4) and (M,4) boxes.

    MultiBox convention (multibox_target-inl.h:153-163): no +1 on widths,
    union<=0 -> 0 (mshadow ``safe_divide``).
    """
    al, at, ar, ab = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bl, bt, br, bb = b[None, :, 0], b[None, :, 1], b[None, :, 2], b[None, :, 3]
    iw = jnp.maximum(0.0, jnp.minimum(ar, br) - jnp.maximum(al, bl))
    ih = jnp.maximum(0.0, jnp.minimum(ab, bb) - jnp.maximum(at, bt))
    inter = iw * ih
    union = ((ar - al) * (ab - at) + (br - bl) * (bb - bt)) - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def _encode_loc(anchors, gt, variances):
    """Corner boxes -> (dx, dy, dw, dh) regression targets
    (multibox_target.cc:30-54 ``AssignLocTargets``)."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    # guard log/div against degenerate (e.g. padded) boxes; masked out later
    aw_s = jnp.where(aw > 0, aw, 1.0)
    ah_s = jnp.where(ah > 0, ah, 1.0)
    ratio_w = jnp.where(gw > 0, gw, 1.0) / aw_s
    ratio_h = jnp.where(gh > 0, gh, 1.0) / ah_s
    return jnp.stack([
        (gx - ax) / aw_s / vx,
        (gy - ay) / ah_s / vy,
        jnp.log(ratio_w) / vw,
        jnp.log(ratio_h) / vh,
    ], axis=1)


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------


def _mbprior_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    sizes = parse_tuple(attrs.get("sizes", "(1.0,)"), typ=float)
    ratios = parse_tuple(attrs.get("ratios", "(1.0,)"), typ=float)
    h, w = data_s[2], data_s[3]
    k = len(sizes) + len(ratios) - 1
    return [data_s], [(1, h * w * k, 4)], []


@register("_contrib_MultiBoxPrior", arg_names=["data"],
          infer_shape=_mbprior_infer_shape, aliases=["MultiBoxPrior"])
def _multibox_prior(ins, attrs, ctx):
    """Generate SSD anchor boxes over the feature-map grid.

    Matches ``MultiBoxPriorForward`` (multibox_prior.cc:30-71): per pixel,
    ``num_sizes`` square boxes then ``num_ratios-1`` boxes at ``sizes[0]``;
    centers at ``(col+offset_x)*step_x, (row+offset_y)*step_y``.
    """
    data = ins[0]
    sizes = parse_tuple(attrs.get("sizes", "(1.0,)"), typ=float)
    ratios = parse_tuple(attrs.get("ratios", "(1.0,)"), typ=float)
    clip = parse_bool(attrs.get("clip", False))
    steps = parse_tuple(attrs.get("steps", "(-1.0, -1.0)"), typ=float)
    offsets = parse_tuple(attrs.get("offsets", "(0.5, 0.5)"), typ=float)
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w

    cy = (jnp.arange(in_h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(in_w, dtype=jnp.float32) + offsets[1]) * step_x
    # per-pixel (w, h) half-extents, ordered exactly as the reference emits
    half = [(s / 2.0, s / 2.0) for s in sizes]
    half += [(sizes[0] * (r ** 0.5) / 2.0, sizes[0] / (r ** 0.5) / 2.0)
             for r in ratios[1:]]
    hw = jnp.asarray(half, dtype=jnp.float32)          # (K, 2)
    k = hw.shape[0]
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")     # (H, W)
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    w2 = hw[None, None, :, 0]
    h2 = hw[None, None, :, 1]
    boxes = jnp.stack([cxg - w2, cyg - h2, cxg + w2, cyg + h2], axis=-1)
    boxes = boxes.reshape(1, in_h * in_w * k, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------


def _mbtarget_infer_shape(in_shapes, attrs):
    anchor_s, label_s, pred_s = in_shapes
    if anchor_s is None or label_s is None:
        return in_shapes, [None, None, None], []
    n = anchor_s[1]
    b = label_s[0]
    return list(in_shapes), [(b, n * 4), (b, n * 4), (b, n)], []


def _match_one(overlaps, valid_gt, overlap_threshold):
    """Greedy bipartite + threshold matching for one batch item.

    overlaps: (N, L) IoU, valid_gt: (L,) bool.  Returns
    (positive (N,) bool, matched_gt (N,) int32, anchor_max_iou (N,)).
    Mirrors multibox_target.cc:109-178: first greedily pair each gt with its
    globally best unmatched anchor (strictly > 1e-6), then mark any other
    anchor whose best-gt IoU exceeds ``overlap_threshold``.
    """
    num_anchors, num_labels = overlaps.shape

    def body(_, carry):
        a_matched, g_matched, match_gt, match_iou = carry
        mask = ((~a_matched)[:, None] & (~g_matched)[None, :]
                & valid_gt[None, :])
        masked = jnp.where(mask, overlaps, _NEG)
        flat = jnp.argmax(masked)
        j = flat // num_labels
        kk = flat % num_labels
        ok = masked[j, kk] > 1e-6
        a_matched = a_matched.at[j].set(jnp.where(ok, True, a_matched[j]))
        g_matched = g_matched.at[kk].set(jnp.where(ok, True, g_matched[kk]))
        match_gt = match_gt.at[j].set(
            jnp.where(ok, kk.astype(jnp.int32), match_gt[j]))
        match_iou = match_iou.at[j].set(
            jnp.where(ok, masked[j, kk], match_iou[j]))
        return a_matched, g_matched, match_gt, match_iou

    init = (jnp.zeros(num_anchors, bool), jnp.zeros(num_labels, bool),
            jnp.full(num_anchors, -1, jnp.int32),
            jnp.full(num_anchors, -1.0, overlaps.dtype))
    bip_matched, _, bip_gt, _ = lax.fori_loop(0, num_labels, body, init)

    # per-anchor best valid gt (the reference computes this lazily in the
    # threshold + mining phases; here it is one masked argmax)
    masked_ov = jnp.where(valid_gt[None, :], overlaps, _NEG)
    best_gt = jnp.argmax(masked_ov, axis=1).astype(jnp.int32)
    max_iou = jnp.max(masked_ov, axis=1)
    max_iou = jnp.where(max_iou <= _NEG / 2, -1.0, max_iou)

    thr_pos = (max_iou > overlap_threshold) & (overlap_threshold > 0)
    positive = bip_matched | thr_pos
    matched_gt = jnp.where(bip_matched, bip_gt, best_gt)
    return positive, matched_gt, max_iou


@register("_contrib_MultiBoxTarget",
          arg_names=["anchor", "label", "cls_pred"], num_outputs=3,
          infer_shape=_mbtarget_infer_shape, aliases=["MultiBoxTarget"])
def _multibox_target(ins, attrs, ctx):
    """Compute SSD training targets (loc_target, loc_mask, cls_target).

    Semantics of ``MultiBoxTargetForward`` (multibox_target.cc:71-280):
    greedy bipartite gt↔anchor matching, threshold matching, optional hard
    negative mining on background softmax prob, variance-encoded location
    targets.  ``minimum_negative_samples`` follows the GPU kernel
    (multibox_target.cu:194-195); the CPU kernel ignores it (default 0 is
    identical).
    """
    anchors, labels, cls_preds = ins
    overlap_threshold = parse_float(attrs.get("overlap_threshold", 0.5))
    ignore_label = parse_float(attrs.get("ignore_label", -1.0))
    mining_ratio = parse_float(attrs.get("negative_mining_ratio", -1.0))
    mining_thresh = parse_float(attrs.get("negative_mining_thresh", 0.5))
    min_negative = parse_int(attrs.get("minimum_negative_samples", 0))
    variances = parse_tuple(attrs.get("variances", "(0.1, 0.1, 0.2, 0.2)"),
                            typ=float)
    anchors2 = anchors.reshape(-1, 4)          # (N, 4)
    num_anchors = anchors2.shape[0]
    num_labels = labels.shape[1]

    def per_batch(label, cls_pred):
        # valid gts = rows before the first class-id == -1 (target.cc:94-103)
        not_pad = label[:, 0] != -1.0
        valid_gt = jnp.cumprod(not_pad.astype(jnp.int32)).astype(bool)
        num_valid = jnp.sum(valid_gt)
        overlaps = _iou_matrix(anchors2, label[:, 1:5])
        positive, matched_gt, max_iou = _match_one(
            overlaps, valid_gt, overlap_threshold)
        num_positive = jnp.sum(positive)

        if mining_ratio > 0:
            # hard negatives: lowest background prob among unmatched anchors
            # below the mining threshold (target.cc:181-240)
            logits = cls_pred                    # (num_classes, N)
            probs = jax.nn.softmax(logits, axis=0)
            bg_prob = probs[0]
            candidate = (~positive) & (max_iou < mining_thresh)
            num_negative = jnp.maximum(
                (num_positive * mining_ratio).astype(jnp.int32), min_negative)
            num_negative = jnp.minimum(num_negative,
                                       num_anchors - num_positive)
            score = jnp.where(candidate, bg_prob, jnp.inf)
            order = jnp.argsort(score, stable=True)
            rank = jnp.zeros(num_anchors, jnp.int32).at[order].set(
                jnp.arange(num_anchors, dtype=jnp.int32))
            negative = candidate & (rank < num_negative)
        else:
            negative = ~positive

        gt_cls = label[:, 0]
        cls_t = jnp.where(
            positive, gt_cls[matched_gt] + 1.0,
            jnp.where(negative, 0.0, ignore_label))
        loc_t = _encode_loc(anchors2, label[:, 1:5][matched_gt], variances)
        loc_t = jnp.where(positive[:, None], loc_t, 0.0)
        loc_m = jnp.where(positive[:, None],
                          jnp.ones((num_anchors, 4), label.dtype), 0.0)
        # no valid gt in this item -> everything stays at init values
        has_gt = num_valid > 0
        cls_t = jnp.where(has_gt, cls_t, ignore_label)
        loc_t = jnp.where(has_gt, loc_t, 0.0)
        loc_m = jnp.where(has_gt, loc_m, 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_target, loc_mask, cls_target = jax.vmap(per_batch)(labels, cls_preds)
    dt = anchors.dtype
    return (loc_target.astype(dt), loc_mask.astype(dt), cls_target.astype(dt))


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------


def _mbdet_infer_shape(in_shapes, attrs):
    cls_s, loc_s, anchor_s = in_shapes
    if cls_s is None:
        return in_shapes, [None], []
    return list(in_shapes), [(cls_s[0], cls_s[2], 6)], []


def _nms_suppress(boxes, ids, valid, nms_threshold, force_suppress):
    """Row-sequential NMS over sorted detections, padded shapes.

    boxes (N,4), ids (N,) (-1 = already invalid), valid (N,) bool.
    Returns suppressed (N,) bool.  Mirrors multibox_detection.cc:148-163.
    """
    n = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes)

    def body(i, suppressed):
        alive_i = (~suppressed[i]) & valid[i] & (ids[i] >= 0)
        same = force_suppress | (ids == ids[i])
        kill = (alive_i & valid & (~suppressed) & same
                & (iou[i] >= nms_threshold)
                & (jnp.arange(n) > i))
        return suppressed | kill

    return lax.fori_loop(0, n, body, jnp.zeros(n, bool))


@register("_contrib_MultiBoxDetection",
          arg_names=["cls_prob", "loc_pred", "anchor"],
          infer_shape=_mbdet_infer_shape, aliases=["MultiBoxDetection"])
def _multibox_detection(ins, attrs, ctx):
    """Decode SSD predictions into [id, score, xmin, ymin, xmax, ymax] rows.

    Matches ``MultiBoxDetectionForward`` (multibox_detection.cc:82-166):
    per-anchor best non-background class, threshold filter, variance-decoded
    boxes, score-descending sort, per-class (or forced) NMS; eliminated and
    invalid rows have id == -1.  Deviation: when ``nms_topk`` cuts the sort,
    the reference leaves rows past the cut in unsorted order AND keeps them
    as NMS targets (detection.cc:141-147); we instead drop rows past the cut
    (id = -1), which is the fixed behavior of later MXNet versions.
    """
    cls_prob, loc_pred, anchors = ins
    threshold = parse_float(attrs.get("threshold", 0.01))
    clip = parse_bool(attrs.get("clip", True))
    nms_threshold = parse_float(attrs.get("nms_threshold", 0.5))
    force_suppress = parse_bool(attrs.get("force_suppress", False))
    nms_topk = parse_int(attrs.get("nms_topk", -1))
    variances = parse_tuple(attrs.get("variances", "(0.1, 0.1, 0.2, 0.2)"),
                            typ=float)
    vx, vy, vw, vh = variances
    anchors2 = anchors.reshape(-1, 4)
    num_anchors = anchors2.shape[0]

    aw = anchors2[:, 2] - anchors2[:, 0]
    ah = anchors2[:, 3] - anchors2[:, 1]
    ax = (anchors2[:, 0] + anchors2[:, 2]) * 0.5
    ay = (anchors2[:, 1] + anchors2[:, 3]) * 0.5

    def per_batch(probs, loc):
        # probs (num_classes, N), loc (N*4,)
        score = jnp.max(probs[1:], axis=0)
        cid = jnp.argmax(probs[1:], axis=0).astype(jnp.float32)  # 0-based
        valid = score >= threshold
        p = loc.reshape(num_anchors, 4)
        ox = p[:, 0] * vx * aw + ax
        oy = p[:, 1] * vy * ah + ay
        ow = jnp.exp(p[:, 2] * vw) * aw * 0.5
        oh = jnp.exp(p[:, 3] * vh) * ah * 0.5
        boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # stable sort: valid rows by descending score, invalid to the back —
        # one sort replaces the reference's compaction + per-batch sort
        key = jnp.where(valid, -score, jnp.inf)
        order = jnp.argsort(key, stable=True)
        s_boxes = boxes[order]
        s_ids = jnp.where(valid[order], cid[order], -1.0)
        s_scores = score[order]
        s_valid = valid[order]
        if nms_topk > 0:
            keep_rank = jnp.arange(num_anchors) < nms_topk
            s_ids = jnp.where(keep_rank, s_ids, -1.0)
            s_valid = s_valid & keep_rank
        if 0 < nms_threshold <= 1:
            suppressed = _nms_suppress(s_boxes, s_ids, s_valid,
                                       nms_threshold, force_suppress)
            s_ids = jnp.where(suppressed, -1.0, s_ids)
        out = jnp.concatenate(
            [s_ids[:, None], s_scores[:, None], s_boxes], axis=1)
        return jnp.where(s_valid[:, None], out, -1.0)

    out = jax.vmap(per_batch)(cls_prob, loc_pred)
    return out.astype(cls_prob.dtype)


# ---------------------------------------------------------------------------
# ROIPooling
# ---------------------------------------------------------------------------


def _roipool_infer_shape(in_shapes, attrs):
    data_s, rois_s = in_shapes
    if data_s is None or rois_s is None:
        return in_shapes, [None], []
    ph, pw = parse_tuple(attrs.get("pooled_size"), typ=int)
    return list(in_shapes), [(rois_s[0], data_s[1], ph, pw)], []


@register("ROIPooling", arg_names=["data", "rois"],
          infer_shape=_roipool_infer_shape, aliases=["_contrib_ROIPooling"])
def _roi_pooling(ins, attrs, ctx):
    """Max-pool features inside each ROI to a fixed (ph, pw) grid.

    Matches ``ROIPoolForward`` (roi_pooling.cc:39-122): rois are
    [batch_idx, x1, y1, x2, y2] scaled by ``spatial_scale`` and rounded;
    malformed rois are forced to 1x1; empty bins output 0.  The gradient is
    jax autodiff of the masked max (reference keeps an explicit argmax aux —
    unnecessary under XLA).
    """
    data, rois = jnp.asarray(ins[0]), jnp.asarray(ins[1])
    ph, pw = parse_tuple(attrs.get("pooled_size"), typ=int)
    spatial_scale = parse_float(attrs.get("spatial_scale", 1.0))
    _, _, height, width = data.shape

    hs = jnp.arange(height)
    ws = jnp.arange(width)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        bin_h = rh.astype(data.dtype) / ph
        bin_w = rw.astype(data.dtype) / pw
        iph = jnp.arange(ph, dtype=data.dtype)
        ipw = jnp.arange(pw, dtype=data.dtype)
        hstart = jnp.clip(jnp.floor(iph * bin_h).astype(jnp.int32) + y1,
                          0, height)
        hend = jnp.clip(jnp.ceil((iph + 1) * bin_h).astype(jnp.int32) + y1,
                        0, height)
        wstart = jnp.clip(jnp.floor(ipw * bin_w).astype(jnp.int32) + x1,
                          0, width)
        wend = jnp.clip(jnp.ceil((ipw + 1) * bin_w).astype(jnp.int32) + x1,
                        0, width)
        hmask = (hs[None, :] >= hstart[:, None]) & (hs[None, :] < hend[:, None])
        wmask = (ws[None, :] >= wstart[:, None]) & (ws[None, :] < wend[:, None])
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]  # ph,pw,H,W
        feat = data[b]                                            # C,H,W
        masked = jnp.where(mask[None], feat[:, None, None, :, :], _NEG)
        pooled = jnp.max(masked, axis=(3, 4))                     # C,ph,pw
        return jnp.where(pooled <= _NEG / 2, 0.0, pooled)

    out = jax.vmap(one_roi)(rois)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Proposal (Faster-RCNN RPN)
# ---------------------------------------------------------------------------


def _proposal_outputs(attrs):
    return 2 if parse_bool(attrs.get("output_score", False)) else 1


def _proposal_infer_shape(in_shapes, attrs):
    cls_s = in_shapes[0]
    post = parse_int(attrs.get("rpn_post_nms_top_n", 300))
    outs = [(post, 5)]
    if parse_bool(attrs.get("output_score", False)):
        outs.append((post, 1))
    return list(in_shapes), outs, []


def _generate_base_anchors(base_size, ratios, scales):
    """Faster-RCNN base anchor enumeration (proposal-inl.h:272-311)."""
    import numpy as np

    w = h = float(base_size)
    x_ctr = 0.5 * (w - 1.0)
    y_ctr = 0.5 * (h - 1.0)
    size = w * h
    out = []
    for r in ratios:
        size_r = np.floor(size / r)
        nw = np.floor(np.sqrt(size_r) + 0.5)
        for s in scales:
            sw = nw * s
            sh = np.floor((nw * r) + 0.5) * s
            out.append([x_ctr - 0.5 * (sw - 1.0), y_ctr - 0.5 * (sh - 1.0),
                        x_ctr + 0.5 * (sw - 1.0), y_ctr + 0.5 * (sh - 1.0)])
    return np.asarray(out, dtype=np.float32)


@register("_contrib_Proposal",
          arg_names=["cls_prob", "bbox_pred", "im_info"],
          num_outputs=_proposal_outputs,
          infer_shape=_proposal_infer_shape,
          aliases=["_contrib_MultiProposal", "Proposal"])
def _proposal(ins, attrs, ctx):
    """RPN proposal generation: shift anchors, decode deltas, clip, filter
    small boxes, sort by score, NMS, pad output to ``rpn_post_nms_top_n``.

    Matches ``ProposalOp::Forward`` (proposal.cc:280-430) including the
    ``keep[i % out_size]`` wrap-around padding of short keep lists.  The
    reference hard-requires batch 1; registered alias
    ``_contrib_MultiProposal`` additionally handles batch > 1 by vmapping
    the same program (multi_proposal.cc shares the kernel).
    """
    import numpy as np

    cls_prob, bbox_pred, im_info = ins
    pre_n = parse_int(attrs.get("rpn_pre_nms_top_n", 6000))
    post_n = parse_int(attrs.get("rpn_post_nms_top_n", 300))
    nms_thresh = parse_float(attrs.get("threshold", 0.7))
    min_size = parse_int(attrs.get("rpn_min_size", 16))
    scales = parse_tuple(attrs.get("scales", "(4, 8, 16, 32)"), typ=float)
    ratios = parse_tuple(attrs.get("ratios", "(0.5, 1, 2)"), typ=float)
    stride = parse_int(attrs.get("feature_stride", 16))
    output_score = parse_bool(attrs.get("output_score", False))

    batch, twoa, fh, fw = cls_prob.shape
    num_anchors = twoa // 2
    count = num_anchors * fh * fw
    pre_n = min(pre_n if pre_n > 0 else count, count)
    post_n = min(post_n, pre_n)

    base = _generate_base_anchors(stride, ratios, scales)  # (A, 4) numpy
    shift_x = np.arange(fw, dtype=np.float32) * stride
    shift_y = np.arange(fh, dtype=np.float32) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)                 # (fh, fw)
    shifts = np.stack([sx, sy, sx, sy], axis=-1)           # (fh, fw, 4)
    # layout index = h*(W*A) + w*A + a  (proposal.cc:347-358)
    all_anchors = (shifts[:, :, None, :] + base[None, None, :, :]).reshape(
        count, 4)
    all_anchors = jnp.asarray(all_anchors)

    def per_image(probs, deltas, info):
        # foreground scores live in the second half of channel dim
        scores = probs[num_anchors:].transpose(1, 2, 0).reshape(count)
        d = deltas.reshape(num_anchors, 4, fh, fw).transpose(2, 3, 0, 1)
        d = d.reshape(count, 4)
        im_h, im_w, im_scale = info[0], info[1], info[2]
        widths = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
        heights = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
        ctr_x = all_anchors[:, 0] + 0.5 * (widths - 1.0)
        ctr_y = all_anchors[:, 1] + 0.5 * (heights - 1.0)
        pcx = d[:, 0] * widths + ctr_x
        pcy = d[:, 1] * heights + ctr_y
        pw_ = jnp.exp(d[:, 2]) * widths
        ph_ = jnp.exp(d[:, 3]) * heights
        x1 = jnp.clip(pcx - 0.5 * (pw_ - 1.0), 0.0, im_w - 1.0)
        y1 = jnp.clip(pcy - 0.5 * (ph_ - 1.0), 0.0, im_h - 1.0)
        x2 = jnp.clip(pcx + 0.5 * (pw_ - 1.0), 0.0, im_w - 1.0)
        y2 = jnp.clip(pcy + 0.5 * (ph_ - 1.0), 0.0, im_h - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)
        # invalidate anchors past the un-padded feature extent
        # (proposal.cc:361-364,83-84)
        real_h = (im_h / stride).astype(jnp.int32)
        real_w = (im_w / stride).astype(jnp.int32)
        hh = jnp.arange(fh)
        ww_ = jnp.arange(fw)
        inside = jnp.broadcast_to(
            (hh[:, None, None] < real_h) & (ww_[None, :, None] < real_w),
            (fh, fw, num_anchors)).reshape(count)
        # small-box filter expands the box and kills the score
        # (proposal.cc:144-157)
        msz = min_size * im_scale
        iw = boxes[:, 2] - boxes[:, 0] + 1.0
        ih = boxes[:, 3] - boxes[:, 1] + 1.0
        small = (iw < msz) | (ih < msz)
        boxes = jnp.where(
            small[:, None],
            boxes + jnp.asarray([-0.5, -0.5, 0.5, 0.5]) * msz, boxes)
        scores = jnp.where(small | (~inside), -1.0, scores)

        order = jnp.argsort(-scores, stable=True)[:pre_n]
        top_boxes = boxes[order]
        top_scores = scores[order]
        # NMS with +1 box areas (proposal.cc:213-262)
        ww = jnp.maximum(
            0.0, jnp.minimum(top_boxes[:, None, 2], top_boxes[None, :, 2])
            - jnp.maximum(top_boxes[:, None, 0], top_boxes[None, :, 0]) + 1.0)
        hh2 = jnp.maximum(
            0.0, jnp.minimum(top_boxes[:, None, 3], top_boxes[None, :, 3])
            - jnp.maximum(top_boxes[:, None, 1], top_boxes[None, :, 1]) + 1.0)
        inter = ww * hh2
        area = ((top_boxes[:, 2] - top_boxes[:, 0] + 1.0)
                * (top_boxes[:, 3] - top_boxes[:, 1] + 1.0))
        iou = inter / (area[:, None] + area[None, :] - inter)

        def body(i, suppressed):
            alive = ~suppressed[i]
            kill = (alive & (iou[i] > nms_thresh)
                    & (jnp.arange(pre_n) > i))
            return suppressed | kill

        suppressed = lax.fori_loop(0, pre_n, body,
                                   jnp.zeros(pre_n, bool))
        keep_mask = ~suppressed
        keep = jnp.argsort(jnp.where(keep_mask, jnp.arange(pre_n),
                                     pre_n + jnp.arange(pre_n)), stable=True)
        out_size = jnp.minimum(jnp.sum(keep_mask), post_n)
        idx = jnp.arange(post_n)
        wrapped = jnp.where(idx < out_size, idx,
                            idx % jnp.maximum(out_size, 1))
        sel = keep[wrapped]
        rois = jnp.concatenate(
            [jnp.zeros((post_n, 1), boxes.dtype), top_boxes[sel]], axis=1)
        out_scores = top_scores[sel][:, None]
        return rois, out_scores

    if batch == 1:
        rois, scores = per_image(cls_prob[0], bbox_pred[0], im_info[0])
    else:
        rois, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
        # rois column 0 is the batch index consumed by ROIPooling
        # (multi_proposal.cu PrepareOutput: out[index*5] = image_index)
        img_idx = jnp.broadcast_to(
            jnp.arange(batch, dtype=rois.dtype)[:, None, None],
            rois.shape[:2] + (1,))
        rois = jnp.concatenate([img_idx, rois[..., 1:]], axis=-1)
        rois = rois.reshape(-1, 5)
        scores = scores.reshape(-1, 1)
    if output_score:
        return rois.astype(cls_prob.dtype), scores.astype(cls_prob.dtype)
    return rois.astype(cls_prob.dtype)
