"""Neural-network layer ops.

Reference analog: the legacy ``MXNET_REGISTER_OP_PROPERTY`` layers —
Convolution/FullyConnected/BatchNorm/Pooling/Activation/SoftmaxOutput/… in
``src/operator/*-inl.h`` with their cuDNN forks (SURVEY.md §2.3).

TPU-native design notes:
- convs lower to ``lax.conv_general_dilated`` → MXU; XLA picks TPU-optimal
  layouts internally, so the *logical* layout stays NCHW (reference default)
  while the physical layout is XLA's choice.  No cuDNN-fork equivalent exists
  or is needed.
- loss layers (``SoftmaxOutput`` family) use ``jax.custom_vjp`` because the
  reference's backward is the loss gradient, not the true derivative of the
  forward (``src/operator/softmax_output-inl.h``).
- ``BatchNorm`` aux state (moving mean/var) is threaded functionally: the op
  returns updated aux, and the executor rebinds them — the functional
  equivalent of the reference mutating aux NDArrays in-place.
- shape back-inference rules mirror ``OperatorProperty::InferShape`` so
  ``simple_bind`` can allocate weights from just the data shape.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register, parse_tuple, parse_bool, parse_int,
                       parse_float)

__all__ = []


# channels-first layouts per spatial rank (reference ConvolutionParam
# layout enum, src/operator/convolution-inl.h; the cuDNN-only NHWC/NDHWC
# variants beyond 2-D NHWC are not lowered — raise instead of silently
# misreading channels-last data as channels-first)
_CF_LAYOUTS = {1: ("NCW",), 2: ("NCHW",), 3: ("NCDHW",)}


def _layout_is_nhwc(attrs, nd):
    layout = attrs.get("layout")
    if layout in (None, "", "None"):
        return False
    if layout == "NHWC" and nd == 2:
        return True
    if layout in _CF_LAYOUTS.get(nd, ()):
        return False
    raise ValueError(
        "unsupported layout %r for %d-d spatial data (supported: %s%s)"
        % (layout, nd, "/".join(_CF_LAYOUTS.get(nd, ())),
           ", NHWC" if nd == 2 else ""))


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------

def _bias_args(no_bias_default):
    """arg-name rule for the FC/conv family: bias arg present unless
    no_bias.  The defaults DIFFER per op in the reference —
    ConvolutionParam no_bias=false, DeconvolutionParam no_bias=TRUE
    (deconvolution-inl.h:90) — one factory keeps the rule in one
    place."""

    def args(attrs):
        if parse_bool(attrs.get("no_bias", no_bias_default)):
            return ["data", "weight"]
        return ["data", "weight", "bias"]

    return args


_fc_args = _bias_args(False)


def _fc_infer_shape(in_shapes, attrs):
    num_hidden = parse_int(attrs.get("num_hidden"))
    no_bias = parse_bool(attrs.get("no_bias", False))
    flatten = parse_bool(attrs.get("flatten", True))
    data_s = in_shapes[0]
    if data_s is not None:
        in_dim = int(np.prod(data_s[1:])) if flatten else data_s[-1]
        w = (num_hidden, in_dim)
        out = (data_s[0], num_hidden) if flatten else tuple(data_s[:-1]) + (num_hidden,)
    else:
        w, out = in_shapes[1], None
    shapes = [data_s, w] + ([] if no_bias else [(num_hidden,)])
    return shapes, [out], []


@register("FullyConnected", arg_names=_fc_args, infer_shape=_fc_infer_shape)
def _fully_connected(ins, attrs, ctx):
    """y = x·Wᵀ + b (``src/operator/fully_connected-inl.h``); weight layout
    (num_hidden, in_dim) as in the reference.  The matmul goes through
    ``quant.site_dot`` — a plain ``jnp.matmul(x, w.T)`` unless a
    quantized-matmul context is active (docs/quantization.md)."""
    from .. import quant

    flatten = parse_bool(attrs.get("flatten", True))
    x = ins[0]
    w = ins[1].astype(x.dtype)  # mixed precision: compute in act dtype
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = quant.site_dot(x, w)
    if len(ins) > 2:
        y = y + ins[2].astype(y.dtype)
    return y


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

_conv_args = _bias_args(False)
_deconv_args = _bias_args(True)


def _conv_out_dim(i, k, s, p, d):
    return (i + 2 * p - (d * (k - 1) + 1)) // s + 1


def _conv_geometry(attrs, nd):
    kernel = parse_tuple(attrs.get("kernel"), nd)
    stride = parse_tuple(attrs.get("stride") or (1,) * nd, nd)
    pad = parse_tuple(attrs.get("pad") or (0,) * nd, nd)
    dilate = parse_tuple(attrs.get("dilate") or (1,) * nd, nd)
    return kernel, stride, pad, dilate


def _conv_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    num_filter = parse_int(attrs.get("num_filter"))
    num_group = parse_int(attrs.get("num_group"), 1)
    no_bias = parse_bool(attrs.get("no_bias", False))
    if data_s is None:
        return in_shapes, [None], []
    nd = len(data_s) - 2
    kernel, stride, pad, dilate = _conv_geometry(attrs, nd)
    nhwc = _layout_is_nhwc(attrs, nd)
    c_in = data_s[-1] if nhwc else data_s[1]
    out_sp = tuple(_conv_out_dim(data_s[(1 if nhwc else 2) + i], kernel[i],
                                 stride[i], pad[i], dilate[i])
                   for i in range(nd))
    # weight stays OIHW in BOTH layouts (initializers' fan-in/fan-out
    # heuristics assume it; XLA's layout assignment transposes for free)
    w = (num_filter, c_in // num_group) + kernel
    out = (data_s[0],) + out_sp + (num_filter,) if nhwc \
        else (data_s[0], num_filter) + out_sp
    shapes = [data_s, w] + ([] if no_bias else [(num_filter,)])
    return shapes, [out], []


_CONV_DIMNUMS = {1: ("NCH", "OIH", "NCH"),
                 2: ("NCHW", "OIHW", "NCHW"),
                 3: ("NCDHW", "OIDHW", "NCDHW")}


@register("Convolution", arg_names=_conv_args, infer_shape=_conv_infer_shape,
          aliases=["Convolution_v1"])
def _convolution(ins, attrs, ctx):
    """N-d convolution (``src/operator/convolution-inl.h:490``); maps to one
    ``lax.conv_general_dilated`` call → MXU.  ``layout="NHWC"`` (the
    reference ConvolutionParam layout option) keeps activations
    channels-last; weights stay OIHW in both layouts so initializer
    fan-in/fan-out heuristics and checkpoints are layout-independent —
    XLA's layout assignment handles the physical transpose (PERF.md)."""
    x, w = ins[0], ins[1].astype(ins[0].dtype)  # bf16 policy: act dtype
    nd = x.ndim - 2
    kernel, stride, pad, dilate = _conv_geometry(attrs, nd)
    num_group = parse_int(attrs.get("num_group"), 1)
    nhwc = _layout_is_nhwc(attrs, nd)
    dimnums = ("NHWC", "OIHW", "NHWC") if nhwc else _CONV_DIMNUMS[nd]
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dimnums,
        feature_group_count=num_group)
    if len(ins) > 2:
        shape = (1,) * (1 + nd) + (-1,) if nhwc else \
            (1, -1) + (1,) * nd
        y = y + ins[2].astype(y.dtype).reshape(shape)
    return y


def _deconv_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    num_filter = parse_int(attrs.get("num_filter"))
    num_group = parse_int(attrs.get("num_group"), 1)
    no_bias = parse_bool(attrs.get("no_bias", True))
    if data_s is None:
        return in_shapes, [None], []
    nd = len(data_s) - 2
    kernel, stride, pad, dilate = _conv_geometry(attrs, nd)
    adj = parse_tuple(attrs.get("adj") or (0,) * nd, nd)
    c_in = data_s[1]
    w = (c_in, num_filter // num_group) + kernel
    out_sp = tuple((data_s[2 + i] - 1) * stride[i] - 2 * pad[i]
                   + (dilate[i] * (kernel[i] - 1) + 1) + adj[i]
                   for i in range(nd))
    out = (data_s[0], num_filter) + out_sp
    shapes = [data_s, w] + ([] if no_bias else [(num_filter,)])
    return shapes, [out], []


@register("Deconvolution", arg_names=_deconv_args,
          infer_shape=_deconv_infer_shape)
def _deconvolution(ins, attrs, ctx):
    """Transposed convolution (``src/operator/deconvolution-inl.h``): the
    gradient of Convolution wrt its input, expressed as lhs-dilated conv."""
    x, w = ins[0], ins[1].astype(ins[0].dtype)  # bf16 policy: act dtype
    nd = x.ndim - 2
    kernel, stride, pad, dilate = _conv_geometry(attrs, nd)
    adj = parse_tuple(attrs.get("adj") or (0,) * nd, nd)
    num_group = parse_int(attrs.get("num_group"), 1)
    # weight (C_in, C_out/g, *k) → conv with flipped spatial + swapped io
    w_t = jnp.swapaxes(w, 0, 1)
    if num_group > 1:
        ci, co_g = w.shape[0], w.shape[1]
        wg = w.reshape((num_group, ci // num_group, co_g) + w.shape[2:])
        w_t = jnp.concatenate([jnp.swapaxes(g, 0, 1) for g in wg], axis=0)
    w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
    lo_hi = [(dilate[i] * (kernel[i] - 1) - pad[i],
              dilate[i] * (kernel[i] - 1) - pad[i] + adj[i])
             for i in range(nd)]
    y = jax.lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nd,
        padding=lo_hi, lhs_dilation=stride, rhs_dilation=dilate,
        dimension_numbers=_CONV_DIMNUMS[nd],
        feature_group_count=num_group)
    if len(ins) > 2:
        y = y + ins[2].astype(y.dtype).reshape((1, -1) + (1,) * nd)
    return y


# ---------------------------------------------------------------------------
# Activation family
# ---------------------------------------------------------------------------

@register("Activation", arg_names=["data"])
def _activation(ins, attrs, ctx):
    act = attrs.get("act_type", "relu")
    x = ins[0]
    if act == "relu":
        return jax.nn.relu(x)
    if act == "sigmoid":
        return jax.nn.sigmoid(x)
    if act == "tanh":
        return jnp.tanh(x)
    if act == "softrelu":
        return jax.nn.softplus(x)
    if act == "softsign":
        return jax.nn.soft_sign(x)
    raise ValueError("unknown act_type %s" % act)


def _leaky_args(attrs):
    if attrs.get("act_type", "leaky") == "prelu":
        return ["data", "gamma"]
    return ["data"]


def _leaky_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if attrs.get("act_type", "leaky") == "prelu":
        g = (data_s[1],) if data_s is not None else in_shapes[1]
        return [data_s, g], [data_s], []
    return [data_s], [data_s], []


@register("LeakyReLU", arg_names=_leaky_args, infer_shape=_leaky_infer_shape,
          needs_rng=True)
def _leaky_relu(ins, attrs, ctx):
    """leaky/elu/prelu/rrelu (``src/operator/leaky_relu-inl.h``)."""
    act = attrs.get("act_type", "leaky")
    x = ins[0]
    slope = parse_float(attrs.get("slope", 0.25))
    if act == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act == "elu":
        return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1.0))
    if act == "prelu":
        g = ins[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, g * x)
    if act == "rrelu":
        lo = parse_float(attrs.get("lower_bound", 0.125))
        hi = parse_float(attrs.get("upper_bound", 0.334))
        if ctx.is_train and ctx.rng is not None:
            a = jax.random.uniform(ctx.rng, x.shape, dtype=x.dtype,
                                   minval=lo, maxval=hi)
        else:
            a = (lo + hi) / 2.0
        return jnp.where(x > 0, x, a * x)
    raise ValueError("unknown act_type %s" % act)


# ---------------------------------------------------------------------------
# softmax family
# ---------------------------------------------------------------------------

@register("softmax", arg_names=["data"])
def _softmax(ins, attrs, ctx):
    axis = parse_int(attrs.get("axis"), -1)
    t = attrs.get("temperature")
    x = ins[0]
    if t not in (None, "None", ""):
        x = x / parse_float(t)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax", arg_names=["data"])
def _log_softmax(ins, attrs, ctx):
    axis = parse_int(attrs.get("axis"), -1)
    return jax.nn.log_softmax(ins[0], axis=axis)


@register("SoftmaxActivation", arg_names=["data"])
def _softmax_activation(ins, attrs, ctx):
    mode = attrs.get("mode", "instance")
    x = ins[0]
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@functools.lru_cache(maxsize=None)
def _softmax_output_fn(grad_scale, ignore_label, use_ignore, multi_output,
                       normalization, smooth_alpha):
    """Build the custom-vjp SoftmaxOutput for one attr combination.

    Reference semantics (``src/operator/softmax_output-inl.h``): forward is
    softmax over the class axis; backward ignores the incoming out_grad and
    emits (p - onehot(label)) · grad_scale, normalized per `normalization`.
    """

    @jax.custom_vjp
    def f(data, label):
        return _fwd_only(data)

    def _fwd_only(data):
        if multi_output:
            return jax.nn.softmax(data, axis=1)
        return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1
                              ).reshape(data.shape)

    def f_fwd(data, label):
        out = _fwd_only(data)
        return out, (out, label)

    def f_bwd(res, g):
        out, label = res
        if multi_output:
            # data (N, C, d...) label (N, d...) — the reference also
            # accepts a size-matched FLAT label, e.g. the RPN feeds
            # (N, A·H·W) against scores (N, 2, A·H/2·W... ) shaped
            # (N, 2, d1, d2) (softmax_output-inl.h flattens to
            # (n, c, rest) internally)
            nclass = out.shape[1]
            spatial = out.shape[:1] + out.shape[2:]
            lab = label
            if lab.shape != spatial and \
                    int(np.prod(lab.shape)) == int(np.prod(spatial)):
                lab = lab.reshape(spatial)
            lab = lab.astype(jnp.int32)
            oh = jnp.moveaxis(jax.nn.one_hot(lab, nclass, dtype=out.dtype),
                              -1, 1)
            grad = out - oh
            if smooth_alpha > 0:
                grad = grad + smooth_alpha / (nclass - 1)
                grad = grad - jnp.moveaxis(
                    jax.nn.one_hot(lab, nclass, dtype=out.dtype), -1, 1) * (
                        smooth_alpha * nclass / (nclass - 1))
            if use_ignore:
                m = jnp.expand_dims((lab != int(ignore_label)), 1)
                grad = grad * m.astype(out.dtype)
                if normalization == "valid":
                    denom = jnp.maximum(m.sum().astype(out.dtype), 1.0)
                    grad = grad / denom
            if normalization == "batch":
                grad = grad / out.shape[0]
            return grad * grad_scale, jnp.zeros_like(label)
        # standard (N, C) case (label (N,))
        flat = out.reshape(out.shape[0], -1)
        nclass = flat.shape[1]
        lab = label.reshape(-1).astype(jnp.int32)
        oh = jax.nn.one_hot(lab, nclass, dtype=flat.dtype)
        grad = flat - oh
        if smooth_alpha > 0:
            grad = grad + smooth_alpha / (nclass - 1) \
                - oh * (smooth_alpha * nclass / (nclass - 1))
        if use_ignore:
            m = (lab != int(ignore_label)).astype(flat.dtype)[:, None]
            grad = grad * m
            if normalization == "valid":
                grad = grad / jnp.maximum(m.sum(), 1.0)
        if normalization == "batch":
            grad = grad / flat.shape[0]
        grad = grad * grad_scale
        return grad.reshape(out.shape), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f


def _softmax_output_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    if parse_bool(attrs.get("multi_output", False)):
        label_s = (data_s[0],) + tuple(data_s[2:])
    else:
        label_s = (data_s[0],)
    return [data_s, in_shapes[1] or label_s], [data_s], []


def _same_as_data_label_infer(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    return [data_s, in_shapes[1] or data_s], [data_s], []


@register("SoftmaxOutput", arg_names=["data", "label"], aliases=["Softmax"],
          infer_shape=_softmax_output_infer_shape)
def _softmax_output(ins, attrs, ctx):
    fn = _softmax_output_fn(
        parse_float(attrs.get("grad_scale", 1.0)),
        parse_float(attrs.get("ignore_label", -1.0)),
        parse_bool(attrs.get("use_ignore", False)),
        parse_bool(attrs.get("multi_output", False)),
        attrs.get("normalization", "null"),
        parse_float(attrs.get("smooth_alpha", 0.0)))
    return fn(ins[0], ins[1])


def _softmax_cross_entropy_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [(1,)], []
    return [data_s, in_shapes[1] or (data_s[0],)], [(1,)], []


@register("softmax_cross_entropy", arg_names=["data", "label"],
          aliases=["SoftmaxCrossEntropy"],
          infer_shape=_softmax_cross_entropy_infer_shape)
def _softmax_cross_entropy(ins, attrs, ctx):
    """Summed cross-entropy of softmax(data) against integer labels.

    Reference: ``src/operator/loss_binary_op.cc:29`` — output is the
    (1,)-shaped TOTAL batch loss; the gradient of the composition is
    the usual ``softmax(data) - onehot(label)``, which plain jax
    autodiff of log-softmax gather recovers (labels flow through an
    integer cast, so they get no gradient, matching the reference's
    label grad of zero).
    """
    data, label = ins
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.reshape(-1).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked).reshape(1)


# ---------------------------------------------------------------------------
# Fused chunked softmax-cross-entropy head
# ---------------------------------------------------------------------------

def _sxh_pick_chunk(n, vocab, requested):
    """Largest divisor of ``n`` whose (chunk, vocab) logits block stays
    near 64M elements — big enough to keep the MXU busy and the (V, E)
    dW accumulator traffic amortized, small enough that the block never
    dominates HBM."""
    if requested > 0:
        target = min(requested, n)
    else:
        target = max(128, min(n, (1 << 26) // max(vocab, 1)))
    for c in range(min(target, n), 0, -1):
        if n % c == 0:
            return c
    return n


@functools.lru_cache(maxsize=None)
def _softmax_xent_head_fn(grad_scale, ignore_label, use_ignore,
                          normalization, chunk):
    """Build the fused projection+softmax+cross-entropy head.

    The LM-head answer to ``SoftmaxOutput``'s O(N·V) materialization
    (reference semantics ``src/operator/softmax_output-inl.h:48``): the
    (N, V) logits/probabilities never exist at once.  Forward scans row
    chunks computing an online logsumexp + target-logit gather; backward
    is a second scan recomputing each chunk's logits (flash-style
    rematerialization) and emitting dX chunks while accumulating dW in
    f32.  Matmuls run in the activation dtype (bf16 on TPU) with f32
    accumulation via ``preferred_element_type``.

    Same loss-head convention as ``SoftmaxOutput``: backward ignores the
    incoming cotangent and emits the cross-entropy gradient scaled by
    ``grad_scale`` (normalization: null | batch | valid).
    """

    def _stats(xc, w, lab_c):
        # one chunk: logits in act dtype with f32 accumulation
        logits = jnp.matmul(xc, w.astype(xc.dtype).T,
                            preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, lab_c[:, None], axis=-1)[:, 0]
        return lse, tgt

    def _fwd_loss(x, w, label):
        n = x.shape[0]
        c = _sxh_pick_chunk(n, w.shape[0], chunk)
        lab = jnp.clip(label.reshape(-1).astype(jnp.int32), 0,
                       w.shape[0] - 1)
        if c == n:
            lse, tgt = _stats(x, w, lab)
        else:
            xs = x.reshape(n // c, c, x.shape[1])
            labs = lab.reshape(n // c, c)
            _, (lse, tgt) = jax.lax.scan(
                lambda _, xl: (None, _stats(xl[0], w, xl[1])),
                None, (xs, labs))
            lse, tgt = lse.reshape(n), tgt.reshape(n)
        loss = lse - tgt
        if use_ignore:
            valid = (label.reshape(-1).astype(jnp.int32)
                     != int(ignore_label))
            loss = jnp.where(valid, loss, 0.0)
        return loss, lse

    @jax.custom_vjp
    def f(x, w, label):
        return _fwd_loss(x, w, label)[0]

    def f_fwd(x, w, label):
        loss, lse = _fwd_loss(x, w, label)
        return loss, (x, w, label, lse)

    def f_bwd(res, g):
        x, w, label, lse = res
        n, e = x.shape
        v = w.shape[0]
        c = _sxh_pick_chunk(n, v, chunk)
        lab_raw = label.reshape(-1).astype(jnp.int32)
        lab = jnp.clip(lab_raw, 0, v - 1)

        scale = jnp.float32(grad_scale)
        if use_ignore:
            valid = (lab_raw != int(ignore_label))
            if normalization == "valid":
                scale = scale / jnp.maximum(
                    valid.sum().astype(jnp.float32), 1.0)
        else:
            valid = None
        if normalization == "batch":
            scale = scale / n
        wc = w.astype(x.dtype)

        def chunk_grads(xc, lab_c, lse_c, valid_c):
            logits = jnp.matmul(xc, wc.T,
                                preferred_element_type=jnp.float32)
            d = jnp.exp(logits - lse_c[:, None])
            d = d - jax.nn.one_hot(lab_c, v, dtype=d.dtype)
            if valid_c is not None:
                d = d * valid_c[:, None].astype(d.dtype)
            d = (d * scale).astype(x.dtype)
            dx_c = jnp.matmul(d, wc)
            dw_c = jnp.matmul(d.T, xc,
                              preferred_element_type=jnp.float32)
            return dx_c, dw_c

        if c == n:
            dx, dw = chunk_grads(x, lab, lse, valid)
        else:
            xs = x.reshape(n // c, c, e)
            labs = lab.reshape(n // c, c)
            lses = lse.reshape(n // c, c)
            valids = valid.reshape(n // c, c) if valid is not None \
                else jnp.zeros((n // c, 0))

            def body(dw_acc, xl):
                xc, lab_c, lse_c, valid_c = xl
                dx_c, dw_c = chunk_grads(
                    xc, lab_c, lse_c,
                    valid_c if use_ignore else None)
                return dw_acc + dw_c, dx_c

            dw, dxs = jax.lax.scan(
                body, jnp.zeros((v, e), jnp.float32),
                (xs, labs, lses, valids))
            dx = dxs.reshape(n, e)
        return dx, dw.astype(w.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f


def _sxh_infer_shape(in_shapes, attrs):
    vocab = parse_int(attrs.get("num_hidden"))
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    w = (vocab, data_s[-1])
    lab = (data_s[0],)
    return [data_s, in_shapes[1] or w, in_shapes[2] or lab], \
        [(data_s[0],)], []


@register("_contrib_SoftmaxXentHead",
          arg_names=["data", "weight", "label"],
          aliases=["SoftmaxXentHead"], infer_shape=_sxh_infer_shape)
def _softmax_xent_head(ins, attrs, ctx):
    """Fused LM head: ``loss[i] = logsumexp(x[i]·Wᵀ) - (x[i]·Wᵀ)[y[i]]``
    over row chunks — O(chunk·V) live memory instead of O(N·V).

    ``data`` (N, E), ``weight`` (num_hidden, E) [the vocab projection],
    ``label`` (N,); output (N,) f32 per-position loss.  Attrs:
    ``num_hidden`` (vocab), ``grad_scale``, ``use_ignore``/
    ``ignore_label``, ``normalization`` (null|batch|valid), ``chunk``
    (row-chunk override, 0 = auto)."""
    fn = _softmax_xent_head_fn(
        parse_float(attrs.get("grad_scale", 1.0)),
        parse_float(attrs.get("ignore_label", -1.0)),
        parse_bool(attrs.get("use_ignore", False)),
        attrs.get("normalization", "null"),
        parse_int(attrs.get("chunk", 0)))
    return fn(ins[0], ins[1], ins[2])


def _regression_output(name, fwd, bwd):
    @functools.lru_cache(maxsize=None)
    def build(grad_scale):
        @jax.custom_vjp
        def f(data, label):
            return fwd(data)

        def f_fwd(data, label):
            return fwd(data), (fwd(data), label)

        def f_bwd(res, g):
            # reference: grad_scale / num_output * (bwd term), no batch
            # normalization (regression_output-inl.h:88-94)
            out, label = res
            n = out.size // out.shape[0] if out.ndim else 1
            grad = bwd(out, label.reshape(out.shape)) * grad_scale
            return grad / max(n, 1), jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return f

    @register(name, arg_names=["data", "label"],
              infer_shape=_same_as_data_label_infer)
    def _f(ins, attrs, ctx, _b=build):
        return _b(parse_float(attrs.get("grad_scale", 1.0)))(ins[0], ins[1])
    return _f


_regression_output("LinearRegressionOutput",
                   lambda x: x, lambda o, l: o - l)
_regression_output("MAERegressionOutput",
                   lambda x: x, lambda o, l: jnp.sign(o - l))
_regression_output("LogisticRegressionOutput",
                   jax.nn.sigmoid, lambda o, l: o - l)


@register("SVMOutput", arg_names=["data", "label"],
          infer_shape=_softmax_output_infer_shape)
def _svm_output(ins, attrs, ctx):
    margin = parse_float(attrs.get("margin", 1.0))
    reg = parse_float(attrs.get("regularization_coefficient", 1.0))
    use_linear = parse_bool(attrs.get("use_linear", False))

    @jax.custom_vjp
    def f(data, label):
        return data

    def f_fwd(data, label):
        return data, (data, label)

    def f_bwd(res, g):
        data, label = res
        n, c = data.shape
        lab = label.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, c, dtype=data.dtype)
        score_y = jnp.sum(data * oh, axis=1, keepdims=True)
        violate = (data - score_y + margin > 0).astype(data.dtype) * (1 - oh)
        if use_linear:
            grad = reg * (violate - oh * violate.sum(axis=1, keepdims=True))
        else:
            m = jnp.maximum(0.0, data - score_y + margin) * (1 - oh)
            grad = reg * 2 * (m - oh * m.sum(axis=1, keepdims=True))
        return grad, jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f(ins[0], ins[1])


# ---------------------------------------------------------------------------
# BatchNorm / InstanceNorm / LayerNorm / LRN
# ---------------------------------------------------------------------------

def _bn_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    axis = parse_int(attrs.get("axis"), 1)
    if data_s is None:
        return in_shapes, [None], [in_shapes[3] if len(in_shapes) > 3 else None] * 2
    c = (data_s[axis],)
    return [data_s, c, c], [data_s], [c, c]


@register("BatchNorm", arg_names=["data", "gamma", "beta"],
          aux_names=["moving_mean", "moving_var"],
          infer_shape=_bn_infer_shape, aliases=["BatchNorm_v1"])
def _batch_norm(ins, attrs, ctx):
    """Batch normalization (``src/operator/batch_norm-inl.h``).  Reference
    defaults: eps=1e-3, momentum=0.9, fix_gamma=True.  Aux (moving mean/var)
    is returned functionally and rebound by the executor."""
    data, gamma, beta, mov_mean, mov_var = ins
    eps = parse_float(attrs.get("eps", 1e-3))
    momentum = parse_float(attrs.get("momentum", 0.9))
    fix_gamma = parse_bool(attrs.get("fix_gamma", True))
    use_global = parse_bool(attrs.get("use_global_stats", False))
    axis = parse_int(attrs.get("axis"), 1)

    # mixed precision: statistics in f32, output cast back to input dtype
    in_dtype = data.dtype
    x32 = data.astype(jnp.float32)
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    if fix_gamma:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    g = gamma.astype(jnp.float32).reshape(bshape)
    b = beta.astype(jnp.float32).reshape(bshape)

    if ctx.is_train and not use_global:
        # single-pass statistics: shifted sum and sum-of-squares fuse
        # into ONE multi-output reduce reading the (bf16) activation once
        # — jnp.var's mean-then-deviation form reads it twice and showed
        # up as 27% of the ResNet-50 step in the xplane trace (PERF.md).
        # The shift K = moving mean kills the E[x²]−E[x]² catastrophic
        # cancellation when |mean| >> std: var = E[(x−K)²] − (E[x−K])²
        # is exact for any K and the error term ∝ (mean−K)² vanishes as
        # the moving mean converges.
        #
        # ghost_sample=k (HBM-roofline lever, PERF.md §17): statistics
        # from the first batch/k rows only — the stat reduce reads 1/k
        # of the activation.  Ghost-BN-style estimator; normalize (and
        # gradients) still cover the full batch.
        ghost = parse_int(attrs.get("ghost_sample", 1))
        xstat = x32
        # axis 0 = stats axis means dim 0 is channels, not batch — no
        # batch axis to subsample; ghost is a no-op there
        if ghost > 1 and axis != 0 and data.shape[0] >= ghost:
            xstat = x32[: data.shape[0] // ghost]
        red_n = float(np.prod([xstat.shape[i] for i in red_axes]))
        shift = jax.lax.stop_gradient(
            mov_mean.astype(jnp.float32)).reshape(bshape)
        xs = xstat - shift
        s = jnp.sum(xs, axis=red_axes)
        s2 = jnp.sum(jnp.square(xs), axis=red_axes)
        d = s / red_n
        mean = d + shift.reshape(d.shape)
        var = jnp.maximum(s2 / red_n - jnp.square(d), 0.0)
        out = (x32 - mean.reshape(bshape)) * jax.lax.rsqrt(
            var.reshape(bshape) + eps) * g + b
        new_mean = mov_mean * momentum + jax.lax.stop_gradient(mean) * (1 - momentum)
        new_var = mov_var * momentum + jax.lax.stop_gradient(var) * (1 - momentum)
        return (out.astype(in_dtype),), (new_mean, new_var)
    out = (x32 - mov_mean.astype(jnp.float32).reshape(bshape)) * \
        jax.lax.rsqrt(mov_var.astype(jnp.float32).reshape(bshape) + eps) \
        * g + b
    return (out.astype(in_dtype),), (mov_mean, mov_var)


def _in_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    c = (data_s[1],)
    return [data_s, c, c], [data_s], []


@register("InstanceNorm", arg_names=["data", "gamma", "beta"],
          infer_shape=_in_infer_shape)
def _instance_norm(ins, attrs, ctx):
    data, gamma, beta = ins
    eps = parse_float(attrs.get("eps", 1e-3))
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape) \
        + beta.reshape(bshape)


def _ln_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    axis = parse_int(attrs.get("axis"), -1)
    if data_s is None:
        return in_shapes, [None], []
    c = (data_s[axis],)
    return [data_s, c, c], [data_s], []


@register("LayerNorm", arg_names=["data", "gamma", "beta"],
          infer_shape=_ln_infer_shape)
def _layer_norm(ins, attrs, ctx):
    """Mixed precision: statistics and affine in f32, output cast back
    to the input dtype — f32 gamma/beta must NOT promote a bf16
    activation stream (a promoted output turns every downstream matmul
    into an f32 MXU op; caught in the round-4 LM xplane trace)."""
    data, gamma, beta = ins
    eps = parse_float(attrs.get("eps", 1e-5))
    axis = parse_int(attrs.get("axis"), -1)
    x32 = data.astype(jnp.float32)
    # two-pass statistics (jnp.var = mean-then-deviation) on purpose:
    # the one-pass E[x²]−mean² form catastrophically cancels for rows
    # with |mean| ≫ std (caught in round-4 review), and the shifted
    # one-pass variant (shift = row's first element, BatchNorm-style)
    # measured SLOWER than two-pass on the LM flagship — the gather +
    # broadcast blocks XLA's reduce fusion (37.0k vs 37.8k tok/s).
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    shp = [1] * data.ndim
    shp[axis] = data.shape[axis]
    y = (x32 - mean) * jax.lax.rsqrt(var + eps) \
        * gamma.astype(jnp.float32).reshape(shp) \
        + beta.astype(jnp.float32).reshape(shp)
    return y.astype(data.dtype)


@register("LRN", arg_names=["data"])
def _lrn(ins, attrs, ctx):
    """Local response normalization across channels
    (``src/operator/lrn-inl.h``)."""
    x = ins[0]
    alpha = parse_float(attrs.get("alpha", 1e-4))
    beta = parse_float(attrs.get("beta", 0.75))
    knorm = parse_float(attrs.get("knorm", 2.0))
    nsize = parse_int(attrs.get("nsize"))
    sq = jnp.square(x)
    half = nsize // 2
    sq_pad = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2))
    win = sum(sq_pad[:, i:i + x.shape[1]] for i in range(nsize))
    return x / jnp.power(knorm + alpha / nsize * win, beta)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

def _pool_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    nd = len(data_s) - 2
    nhwc = _layout_is_nhwc(attrs, nd)
    sp0 = 1 if nhwc else 2  # first spatial dim index

    def out_shape(sp):
        if nhwc:
            return (data_s[0],) + tuple(sp) + (data_s[-1],)
        return tuple(data_s[:2]) + tuple(sp)

    if parse_bool(attrs.get("global_pool", False)):
        return [data_s], [out_shape((1,) * nd)], []
    kernel = parse_tuple(attrs.get("kernel"), nd)
    stride = parse_tuple(attrs.get("stride") or (1,) * nd, nd)
    pad = parse_tuple(attrs.get("pad") or (0,) * nd, nd)
    conv = attrs.get("pooling_convention", "valid")
    out_sp = []
    for i in range(nd):
        num = data_s[sp0 + i] + 2 * pad[i] - kernel[i]
        if conv == "full":
            o = int(np.ceil(num / stride[i])) + 1
        else:
            o = num // stride[i] + 1
        out_sp.append(o)
    return [data_s], [out_shape(out_sp)], []


@register("Pooling", arg_names=["data"], infer_shape=_pool_infer_shape,
          aliases=["Pooling_v1"])
def _pooling(ins, attrs, ctx):
    """max/avg/sum pooling (``src/operator/pooling-inl.h``) via
    ``lax.reduce_window``; ``layout="NHWC"`` pools channels-last."""
    x = ins[0]
    nd = x.ndim - 2
    ptype = attrs.get("pool_type", "max")
    nhwc = _layout_is_nhwc(attrs, nd)
    sp0 = 1 if nhwc else 2
    if parse_bool(attrs.get("global_pool", False)):
        red = tuple(range(sp0, sp0 + nd))
        if ptype == "max":
            return jnp.max(x, axis=red, keepdims=True)
        if ptype == "sum":
            return jnp.sum(x, axis=red, keepdims=True)
        return jnp.mean(x, axis=red, keepdims=True)
    kernel, stride, pad, _ = _conv_geometry(attrs, nd)
    conv = attrs.get("pooling_convention", "valid")
    # output size per convention; 'full' (ceil) needs extra right padding
    extra = [0] * nd
    for i in range(nd):
        num = x.shape[sp0 + i] + 2 * pad[i] - kernel[i]
        if conv == "full":
            o = int(np.ceil(num / stride[i])) + 1
        else:
            o = num // stride[i] + 1
        extra[i] = max(0, (o - 1) * stride[i] + kernel[i]
                       - (x.shape[sp0 + i] + 2 * pad[i]))
    sp_pads = [(pad[i], pad[i] + extra[i]) for i in range(nd)]
    if nhwc:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + sp_pads + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + sp_pads
    if ptype == "max":
        init = -jnp.inf
        y = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
        return y
    y = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if ptype == "sum":
        return y
    # avg: divide by true window size (count includes padding in reference
    # v0.11 mshadow pool? — reference uses full kernel size divisor)
    return y / float(np.prod(kernel))


@register("UpSampling", arg_names=None, num_outputs=1)
def _upsampling(ins, attrs, ctx):
    """nearest/bilinear upsampling (``src/operator/upsampling-inl.h``)."""
    scale = parse_int(attrs.get("scale"))
    sample_type = attrs.get("sample_type", "nearest")
    x = ins[0]
    if sample_type == "nearest":
        # output is (scale·h0, scale·w0); every other input is upsampled
        # to that size (upsampling-inl.h num_args doc), then concat along
        # channels or summed per multi_input_mode
        out_h, out_w = x.shape[2] * scale, x.shape[3] * scale
        outs = []
        for x in ins:
            y = jnp.repeat(jnp.repeat(x, out_h // x.shape[2], axis=2),
                           out_w // x.shape[3], axis=3)
            outs.append(y)
        if len(outs) > 1:
            if attrs.get("multi_input_mode", "concat") == "sum":
                return sum(outs[1:], outs[0])
            return jnp.concatenate(outs, axis=1)
        return outs[0]
    # bilinear via resize (weight input ignored: resize kernel is fixed)
    x = ins[0]
    n, c, h, w = x.shape
    return jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------

@register("Dropout", arg_names=["data"], needs_rng=True)
def _dropout(ins, attrs, ctx):
    """Inverted dropout (``src/operator/dropout-inl.h``): scale by 1/(1-p) at
    train time, identity at inference."""
    x = ins[0]
    p = parse_float(attrs.get("p", 0.5))
    mode = attrs.get("mode", "training")
    if (not ctx.is_train and mode != "always") or p <= 0.0 or ctx.rng is None:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Misc layers
# ---------------------------------------------------------------------------

@register("Crop", arg_names=None)
def _crop(ins, attrs, ctx):
    """Crop to like-shape or explicit h_w (``src/operator/crop-inl.h``)."""
    x = ins[0]
    offset = parse_tuple(attrs.get("offset") or (0, 0), 2)
    h_w = attrs.get("h_w")
    if len(ins) > 1:
        th, tw = ins[1].shape[2], ins[1].shape[3]
    else:
        th, tw = parse_tuple(h_w, 2)
    if parse_bool(attrs.get("center_crop", False)):
        oh = (x.shape[2] - th) // 2
        ow = (x.shape[3] - tw) // 2
    else:
        oh, ow = offset
    return x[:, :, oh:oh + th, ow:ow + tw]


@register("BilinearSampler", arg_names=["data", "grid"])
def _bilinear_sampler(ins, attrs, ctx):
    """Bilinear sampling from a flow grid
    (``src/operator/bilinear_sampler-inl.h``); grid in [-1, 1]."""
    data, grid = ins
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        bidx = jnp.arange(n)[:, None, None]
        return data[bidx, :, yi, xi]  # (n, oh, ow, c)

    out = (gather(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
           + gather(y0, x0 + 1) * ((1 - wy) * wx)[..., None]
           + gather(y0 + 1, x0) * (wy * (1 - wx))[..., None]
           + gather(y0 + 1, x0 + 1) * (wy * wx)[..., None])
    return jnp.moveaxis(out, -1, 1)


@register("GridGenerator", arg_names=["data"])
def _grid_generator(ins, attrs, ctx):
    """affine/warp grid generation (``src/operator/grid_generator-inl.h``)."""
    transform = attrs.get("transform_type", "affine")
    data = ins[0]
    th, tw = parse_tuple(attrs.get("target_shape"), 2)
    ys = jnp.linspace(-1.0, 1.0, th)
    xs = jnp.linspace(-1.0, 1.0, tw)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    if transform == "affine":
        base = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                          jnp.ones(th * tw)], axis=0)
        theta = data.reshape(-1, 2, 3)
        out = jnp.matmul(theta, base)  # (n, 2, th*tw)
        return out.reshape(-1, 2, th, tw)
    # warp: data is flow (n, 2, h, w)
    norm = jnp.stack([gx, gy])[None]
    flow = data / jnp.asarray([tw / 2.0, th / 2.0]).reshape(1, 2, 1, 1)
    return norm + flow


@register("SpatialTransformer", arg_names=["data", "loc"])
def _spatial_transformer(ins, attrs, ctx):
    data, loc = ins
    th, tw = parse_tuple(attrs.get("target_shape"), 2)
    grid = _grid_generator([loc], {"transform_type": "affine",
                                   "target_shape": (th, tw)}, ctx)
    return _bilinear_sampler([data, grid], {}, ctx)
