"""Matrix/shape-manipulation ops.

Reference analog: ``src/operator/tensor/matrix_op*`` (dot, transpose, reshape,
slice, clip, repeat, tile, …; SURVEY.md §2.3).  ``dot`` maps straight onto the
MXU via ``jax.lax.dot_general``; everything else is metadata-only in XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, parse_tuple, parse_bool, parse_int, parse_float

__all__ = []


@register("dot", arg_names=["lhs", "rhs"])
def _dot(ins, attrs, ctx):
    a, b = ins
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    if ta:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if tb:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # reference dot: reduce last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", arg_names=["lhs", "rhs"])
def _batch_dot(ins, attrs, ctx):
    a, b = ins
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _infer_reshape(shape, target):
    """Implements the reference reshape codes 0 (keep), -1 (infer),
    -2 (copy rest), -3 (merge two), -4 (split) —
    ``src/operator/tensor/matrix_op-inl.h`` semantics."""
    out = []
    src = list(shape)
    i = 0
    t = list(target)
    j = 0
    while j < len(t):
        d = t[j]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            a, b = t[j + 1], t[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(d)
            if i < len(src):
                i += 1
        j += 1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(shape)) if shape else 1
        out[out.index(-1)] = total // known
    return tuple(out)


@register("Reshape", arg_names=["data"], aliases=["reshape"])
def _reshape(ins, attrs, ctx):
    x = ins[0]
    shape = parse_tuple(attrs.get("shape"))
    if parse_bool(attrs.get("reverse", False)):
        rev = _infer_reshape(x.shape[::-1], tuple(shape)[::-1])
        return x.reshape(rev[::-1])
    return x.reshape(_infer_reshape(x.shape, shape))


@register("Flatten", arg_names=["data"], aliases=["flatten"])
def _flatten(ins, attrs, ctx):
    x = ins[0]
    return x.reshape(x.shape[0], -1)


@register("transpose", arg_names=["data"])
def _transpose(ins, attrs, ctx):
    axes = attrs.get("axes")
    axes = parse_tuple(axes) if axes not in (None, "", ()) else None
    return jnp.transpose(ins[0], axes)


@register("expand_dims", arg_names=["data"])
def _expand_dims(ins, attrs, ctx):
    return jnp.expand_dims(ins[0], parse_int(attrs.get("axis")))


@register("squeeze", arg_names=["data"])
def _squeeze(ins, attrs, ctx):
    axis = attrs.get("axis")
    if axis in (None, ""):
        return jnp.squeeze(ins[0])
    return jnp.squeeze(ins[0], parse_tuple(axis))


@register("slice", arg_names=["data"], aliases=["crop"])
def _slice(ins, attrs, ctx):
    x = ins[0]
    begin = parse_tuple(attrs.get("begin"))
    end = parse_tuple(attrs.get("end"))
    step = attrs.get("step")
    step = parse_tuple(step) if step not in (None, "", ()) else (1,) * len(begin)
    idx = []
    for i in range(x.ndim):
        if i < len(begin):
            b = begin[i]
            e = end[i] if i < len(end) else None
            s = step[i] if i < len(step) else 1
            b = None if b is None else b
            idx.append(slice(b, e, s if s != 0 else 1))
        else:
            idx.append(slice(None))
    return x[tuple(idx)]


@register("slice_axis", arg_names=["data"])
def _slice_axis(ins, attrs, ctx):
    x = ins[0]
    axis = parse_int(attrs.get("axis"))
    begin = parse_int(attrs.get("begin"), 0)
    end = attrs.get("end")
    end = None if end in (None, "None", "") else parse_int(end)
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", arg_names=["data", "shape_like"])
def _slice_like(ins, attrs, ctx):
    x, like = ins
    axes = attrs.get("axes")
    axes = parse_tuple(axes) if axes not in (None, "", ()) else tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


@register("repeat", arg_names=["data"])
def _repeat(ins, attrs, ctx):
    x = ins[0]
    repeats = parse_int(attrs.get("repeats"))
    axis = attrs.get("axis")
    if axis in (None, ""):
        return jnp.repeat(x.reshape(-1), repeats)
    return jnp.repeat(x, repeats, axis=parse_int(axis))


@register("tile", arg_names=["data"])
def _tile(ins, attrs, ctx):
    return jnp.tile(ins[0], parse_tuple(attrs.get("reps")))


@register("reverse", arg_names=["data"], aliases=["flip"])
def _reverse(ins, attrs, ctx):
    return jnp.flip(ins[0], parse_tuple(attrs.get("axis")))


@register("Concat", arg_names=None, aliases=["concat"])
def _concat(ins, attrs, ctx):
    dim = parse_int(attrs.get("dim"), 1)
    return jnp.concatenate(ins, axis=dim)


@register("stack", arg_names=None)
def _stack(ins, attrs, ctx):
    return jnp.stack(ins, axis=parse_int(attrs.get("axis"), 0))


def _split_infer_shape(in_shapes, attrs, n_out):
    pass


@register("SliceChannel", arg_names=["data"], aliases=["split"],
          num_outputs=-1)
def _slice_channel(ins, attrs, ctx):
    """Split along an axis into num_outputs parts
    (``src/operator/slice_channel-inl.h``)."""
    x = ins[0]
    num = parse_int(attrs.get("num_outputs"))
    axis = parse_int(attrs.get("axis"), 1)
    squeeze = parse_bool(attrs.get("squeeze_axis", False))
    parts = jnp.split(x, num, axis=axis)
    if squeeze:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("SwapAxis", arg_names=["data"], aliases=["swapaxes"])
def _swapaxes(ins, attrs, ctx):
    return jnp.swapaxes(ins[0], parse_int(attrs.get("dim1"), 0),
                        parse_int(attrs.get("dim2"), 0))


@register("Pad", arg_names=["data"], aliases=["pad"])
def _pad(ins, attrs, ctx):
    """N-D padding (``src/operator/pad-inl.h``): pad_width is
    (before, after) per axis flattened, mode constant/edge/reflect."""
    x = ins[0]
    pw = parse_tuple(attrs.get("pad_width"))
    mode = attrs.get("mode", "constant")
    cval = parse_float(attrs.get("constant_value", 0.0))
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    while len(pairs) < x.ndim:
        pairs.append((0, 0))
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=cval)
    return jnp.pad(x, pairs, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register("L2Normalization", arg_names=["data"])
def _l2norm(ins, attrs, ctx):
    x = ins[0]
    eps = parse_float(attrs.get("eps", 1e-10))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axis = tuple(range(1, x.ndim))
    elif mode == "channel":
        axis = (1,)
    else:  # spatial
        axis = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return x / norm


@register("diag", arg_names=["data"])
def _diag(ins, attrs, ctx):
    return jnp.diag(ins[0], k=parse_int(attrs.get("k"), 0))


@register("space_to_depth", arg_names=["data"])
def _space_to_depth(ins, attrs, ctx):
    x = ins[0]
    bs = parse_int(attrs.get("block_size"))
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


@register("depth_to_space", arg_names=["data"])
def _depth_to_space(ins, attrs, ctx):
    x = ins[0]
    bs = parse_int(attrs.get("block_size"))
    n, c, h, w = x.shape
    x = x.reshape(n, bs, bs, c // (bs * bs), h, w)
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return x.reshape(n, c // (bs * bs), h * bs, w * bs)
