"""Contrib vision/sequence ops absent from the round-2 build: Correlation,
CTCLoss, PSROIPooling, DeformablePSROIPooling, DeformableConvolution and
krprod — each a static-shape XLA program (gathers + matmuls instead of the
reference's hand-written CUDA kernels).

Reference files:
- ``src/operator/correlation-inl.h:45-120`` + ``correlation.cc:40-80``
- ``src/operator/contrib/ctc_loss-inl.h:98-281`` (warp-ctc semantics:
  blank = 0, labels 0-padded, activations get softmax inside the op)
- ``src/operator/contrib/psroi_pooling-inl.h:51`` + ``psroi_pooling.cu:50``
- ``src/operator/contrib/deformable_psroi_pooling-inl.h:51`` +
  ``deformable_psroi_pooling.cu:71-170``
- ``src/operator/contrib/deformable_convolution-inl.h:58`` +
  ``nn/deformable_im2col.cuh`` (bilinear-offset im2col)
- ``src/operator/contrib/krprod.h:49`` (row-wise Khatri-Rao)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register, parse_bool, parse_float, parse_int,
                       parse_tuple)

__all__ = []

_NEG = -1e30


# ---------------------------------------------------------------------------
# Correlation (FlowNet)
# ---------------------------------------------------------------------------

def _corr_geometry(shape, attrs):
    h, w = shape[2], shape[3]
    pad = parse_int(attrs.get("pad_size"), 0)
    ksize = parse_int(attrs.get("kernel_size"), 1)
    max_disp = parse_int(attrs.get("max_displacement"), 1)
    s1 = parse_int(attrs.get("stride1"), 1)
    s2 = parse_int(attrs.get("stride2"), 1)
    kr = (ksize - 1) // 2
    border = max_disp + kr
    ph, pw = h + 2 * pad, w + 2 * pad
    top_w = int(np.ceil((pw - border * 2) / s1))
    top_h = int(np.ceil((ph - border * 2) / s1))
    rad = max_disp // s2
    grid_w = rad * 2 + 1
    return (pad, ksize, max_disp, s1, s2, kr, top_h, top_w, rad, grid_w)


def _correlation_infer_shape(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    (_, _, _, _, _, _, th, tw, _, gw) = _corr_geometry(d, attrs)
    return [d, in_shapes[1] or d], [(d[0], gw * gw, th, tw)], []


@register("Correlation", arg_names=["data1", "data2"],
          infer_shape=_correlation_infer_shape)
def _correlation(ins, attrs, ctx):
    """Correlation of two feature maps over a displacement neighborhood
    (``correlation.cc:40-80``): output channel (p, o) is the
    kernel-window product (or abs-difference) of data1 at (y1, x1) with
    data2 at (y1 + p·stride2, x1 + o·stride2), normalized by kernel²·C.
    (y1, x1) is the window's top-left in the padded map, exactly as the
    reference indexes ``tmp1[y1+h][x1+w]``."""
    x1, x2 = ins
    n, c, h, w = x1.shape
    (pad, ksize, max_disp, s1, s2, kr, top_h, top_w, rad, grid_w) = \
        _corr_geometry(x1.shape, attrs)
    is_mult = parse_bool(attrs.get("is_multiply", True))
    p1 = jnp.pad(x1, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    p2 = jnp.pad(x2, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    sumelems = ksize * ksize * c

    ys = jnp.arange(top_h) * s1 + max_disp
    xs = jnp.arange(top_w) * s1 + max_disp
    ky = jnp.arange(ksize)
    kx = jnp.arange(ksize)

    def patches(img, dy, dx):
        """(N, C, top_h, top_w, k, k) kernel windows displaced (dy, dx)."""
        rows = (ys[:, None] + dy + ky[None, :])  # (top_h, k)
        cols = (xs[:, None] + dx + kx[None, :])  # (top_w, k)
        rows = rows[:, None, :, None]
        cols = cols[None, :, None, :]
        rows = jnp.broadcast_to(rows, (top_h, top_w, ksize, ksize))
        cols = jnp.broadcast_to(cols, (top_h, top_w, ksize, ksize))
        return img[:, :, rows, cols]

    base = patches(p1, 0, 0)
    outs = []
    for p in range(-rad, rad + 1):
        for o in range(-rad, rad + 1):
            disp = patches(p2, p * s2, o * s2)
            v = base * disp if is_mult else jnp.abs(base - disp)
            outs.append(v.sum(axis=(1, 4, 5)) / sumelems)
    return jnp.stack(outs, axis=1).astype(x1.dtype)


# ---------------------------------------------------------------------------
# CTCLoss (warp-ctc semantics)
# ---------------------------------------------------------------------------

def _ctc_infer_shape(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    return [d, in_shapes[1]], [(d[1],)], []


@register("_contrib_CTCLoss", arg_names=["data", "label"],
          aliases=["CTCLoss", "ctc_loss"], infer_shape=_ctc_infer_shape)
def _ctc_loss(ins, attrs, ctx):
    """CTC negative log-likelihood (``ctc_loss-inl.h``): data (T, N, C)
    raw activations (softmax applied inside, warp-ctc contract), label
    (N, L) 0-padded (0 is the blank).  Log-space alpha recursion as one
    ``lax.scan``; the gradient is jax's autodiff of the loss — the same
    (softmax − expected-counts) gradient warp-ctc computes analytically."""
    data, labels = ins
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    lab = jax.lax.stop_gradient(labels).astype(jnp.int32)
    L = lab.shape[1]
    lab_len = jnp.sum((lab != 0).astype(jnp.int32), axis=1)
    S = 2 * L + 1
    ext = jnp.zeros((N, S), jnp.int32).at[:, 1::2].set(lab)
    s_valid = 2 * lab_len + 1
    smask = jnp.arange(S)[None, :] < s_valid[:, None]
    can_skip = jnp.zeros((N, S), bool).at[:, 2:].set(
        (ext[:, 2:] != 0) & (ext[:, 2:] != ext[:, :-2]))

    def emit(logp_t):
        return jnp.take_along_axis(logp_t, ext, axis=1)  # (N, S)

    alpha0 = jnp.full((N, S), _NEG, jnp.float32)
    e0 = emit(logp[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    if S > 1:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, e0[:, 1], _NEG))
    alpha0 = jnp.where(smask, alpha0, _NEG)

    def step(alpha, logp_t):
        e = emit(logp_t)
        s1 = jnp.concatenate(
            [jnp.full((N, 1), _NEG, alpha.dtype), alpha[:, :-1]], axis=1)
        s2 = jnp.concatenate(
            [jnp.full((N, 2), _NEG, alpha.dtype), alpha[:, :-2]], axis=1)
        s2 = jnp.where(can_skip, s2, _NEG)
        m = jnp.maximum(jnp.maximum(alpha, s1), s2)
        tot = m + jnp.log(jnp.exp(alpha - m) + jnp.exp(s1 - m)
                          + jnp.exp(s2 - m))
        a = tot + e
        return jnp.where(smask, a, _NEG), None

    alpha, _ = jax.lax.scan(step, alpha0, logp[1:])
    last1 = jnp.take_along_axis(alpha, (s_valid - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(s_valid - 2, 0)[:, None], axis=1)[:, 0]
    total = jnp.where(s_valid >= 2, jnp.logaddexp(last1, last2), last1)
    return (-total).astype(data.dtype)


# ---------------------------------------------------------------------------
# PSROIPooling / DeformablePSROIPooling
# ---------------------------------------------------------------------------

def _psroi_infer_shape(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    out_dim = parse_int(attrs.get("output_dim"))
    pooled = parse_int(attrs.get("pooled_size"))
    r = in_shapes[1][0] if in_shapes[1] is not None else None
    out = None if r is None else (r, out_dim, pooled, pooled)
    return list(in_shapes), [out], []


@register("_contrib_PSROIPooling", arg_names=["data", "rois"],
          aliases=["PSROIPooling"], infer_shape=_psroi_infer_shape)
def _psroi_pooling(ins, attrs, ctx):
    """Position-sensitive ROI average pooling (``psroi_pooling.cu:50-116``):
    output bin (ctop, ph, pw) averages input channel
    (ctop·G + gh)·G + gw over the bin's integer footprint."""
    data, rois = ins
    n, channels, height, width = data.shape
    scale = parse_float(attrs.get("spatial_scale"))
    out_dim = parse_int(attrs.get("output_dim"))
    pooled = parse_int(attrs.get("pooled_size"))
    gsize = parse_int(attrs.get("group_size"), 0) or pooled

    pidx = jnp.arange(pooled, dtype=jnp.float32)
    g_of_p = jnp.clip((jnp.arange(pooled) * gsize) // pooled, 0, gsize - 1)

    def one_roi(roi):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale
        y1 = jnp.round(roi[2]) * scale
        x2 = (jnp.round(roi[3]) + 1.0) * scale
        y2 = (jnp.round(roi[4]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / pooled, rw / pooled
        img = data[batch]  # (C, H, W)

        hh = jnp.arange(height, dtype=jnp.float32)
        ww = jnp.arange(width, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(pidx * bh + y1), 0, height)
        hend = jnp.clip(jnp.ceil((pidx + 1) * bh + y1), 0, height)
        wstart = jnp.clip(jnp.floor(pidx * bw + x1), 0, width)
        wend = jnp.clip(jnp.ceil((pidx + 1) * bw + x1), 0, width)
        hm = (hh[None, :] >= hstart[:, None]) & (hh[None, :] < hend[:, None])
        wm = (ww[None, :] >= wstart[:, None]) & (ww[None, :] < wend[:, None])
        # per-channel bin sums: (C, P, P)
        sums = jnp.einsum("chw,ph,qw->cpq", img, hm.astype(img.dtype),
                          wm.astype(img.dtype))
        area = (hend - hstart)[:, None] * (wend - wstart)[None, :]
        empty = (hend[:, None] <= hstart[:, None]) | \
            (wend[None, :] <= wstart[None, :])
        avg = jnp.where(empty[None], 0.0,
                        sums / jnp.maximum(area, 1.0)[None])
        # position-sensitive channel per (ctop, ph, pw)
        cmap = (jnp.arange(out_dim)[:, None, None] * gsize
                + g_of_p[None, :, None]) * gsize + g_of_p[None, None, :]
        return avg[cmap, jnp.arange(pooled)[None, :, None],
                   jnp.arange(pooled)[None, None, :]]

    return jax.vmap(one_roi)(rois).astype(data.dtype)


def _bilinear_clamped(img_c, y, x, height, width):
    """Bilinear sample of img_c (H, W) with coords pre-clamped into the
    map (``deformable_psroi_pooling.cu`` bilinear_interp contract)."""
    y = jnp.clip(y, 0.0, height - 1.0)
    x = jnp.clip(x, 0.0, width - 1.0)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.minimum(y0 + 1, height - 1)
    x1 = jnp.minimum(x0 + 1, width - 1)
    wy, wx = y - y0, x - x0
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    return (img_c[y0i, x0i] * (1 - wy) * (1 - wx)
            + img_c[y0i, x1i] * (1 - wy) * wx
            + img_c[y1i, x0i] * wy * (1 - wx)
            + img_c[y1i, x1i] * wy * wx)


def _dpsroi_args(attrs):
    if parse_bool(attrs.get("no_trans", False)):
        return ["data", "rois"]
    return ["data", "rois", "trans"]


@register("_contrib_DeformablePSROIPooling", arg_names=_dpsroi_args,
          aliases=["DeformablePSROIPooling"],
          infer_shape=_psroi_infer_shape)
def _deformable_psroi_pooling(ins, attrs, ctx):
    """Deformable position-sensitive ROI pooling
    (``deformable_psroi_pooling.cu:71-170``): each bin is shifted by a
    learned normalized offset (trans · trans_std · roi size) and averaged
    over sample_per_part² bilinear samples."""
    data, rois = ins[0], ins[1]
    trans = ins[2] if len(ins) > 2 else None
    n, channels, height, width = data.shape
    scale = parse_float(attrs.get("spatial_scale"))
    out_dim = parse_int(attrs.get("output_dim"))
    pooled = parse_int(attrs.get("pooled_size"))
    gsize = parse_int(attrs.get("group_size"))
    part = parse_int(attrs.get("part_size"), 0) or pooled
    spp = parse_int(attrs.get("sample_per_part"), 1)
    trans_std = parse_float(attrs.get("trans_std", 0.0))
    no_trans = parse_bool(attrs.get("no_trans", False)) or trans is None
    num_classes = 1 if no_trans else trans.shape[1] // 2
    ch_per_class = max(out_dim // num_classes, 1)

    p_idx = jnp.arange(pooled)
    g_of_p = jnp.clip((p_idx * gsize) // pooled, 0, gsize - 1)
    part_of_p = jnp.clip((p_idx * part) // pooled, 0, part - 1)

    def one_roi(roi, roi_idx):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale - 0.5
        y1 = jnp.round(roi[2]) * scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / pooled, rw / pooled
        sbh, sbw = bh / spp, bw / spp
        img = data[batch]

        def one_cell(ctop, ph, pw):
            cls = ctop // ch_per_class
            if no_trans:
                tx = jnp.asarray(0.0)
                ty = jnp.asarray(0.0)
            else:
                tx = trans[roi_idx, cls * 2, part_of_p[ph],
                           part_of_p[pw]] * trans_std
                ty = trans[roi_idx, cls * 2 + 1, part_of_p[ph],
                           part_of_p[pw]] * trans_std
            wstart = pw * bw + x1 + tx * rw
            hstart = ph * bh + y1 + ty * rh
            c = (ctop * gsize + g_of_p[ph]) * gsize + g_of_p[pw]
            iw = jnp.arange(spp, dtype=jnp.float32)
            wg, hg = jnp.meshgrid(wstart + iw * sbw, hstart + iw * sbh)
            valid = ((wg >= -0.5) & (wg <= width - 0.5)
                     & (hg >= -0.5) & (hg <= height - 0.5))
            vals = _bilinear_clamped(img[c], hg.reshape(-1), wg.reshape(-1),
                                     height, width).reshape(spp, spp)
            cnt = valid.sum()
            return jnp.where(cnt == 0, 0.0,
                             jnp.sum(vals * valid) / jnp.maximum(cnt, 1))

        return jax.vmap(lambda ct: jax.vmap(lambda ph: jax.vmap(
            lambda pw: one_cell(ct, ph, pw))(p_idx))(p_idx))(
                jnp.arange(out_dim))

    return jax.vmap(one_roi)(rois, jnp.arange(rois.shape[0])
                             ).astype(data.dtype)


# ---------------------------------------------------------------------------
# DeformableConvolution (DCN v1)
# ---------------------------------------------------------------------------

def _dconv_args(attrs):
    if parse_bool(attrs.get("no_bias", False)):
        return ["data", "offset", "weight"]
    return ["data", "offset", "weight", "bias"]


def _dconv_infer_shape(in_shapes, attrs):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    num_filter = parse_int(attrs.get("num_filter"))
    num_group = parse_int(attrs.get("num_group"), 1)
    dg = parse_int(attrs.get("num_deformable_group"), 1)
    no_bias = parse_bool(attrs.get("no_bias", False))
    kernel = parse_tuple(attrs.get("kernel"), 2)
    stride = parse_tuple(attrs.get("stride") or (1, 1), 2)
    pad = parse_tuple(attrs.get("pad") or (0, 0), 2)
    dilate = parse_tuple(attrs.get("dilate") or (1, 1), 2)
    oh = (data_s[2] + 2 * pad[0] - (dilate[0] * (kernel[0] - 1) + 1)) \
        // stride[0] + 1
    ow = (data_s[3] + 2 * pad[1] - (dilate[1] * (kernel[1] - 1) + 1)) \
        // stride[1] + 1
    w = (num_filter, data_s[1] // num_group) + tuple(kernel)
    off = (data_s[0], dg * 2 * kernel[0] * kernel[1], oh, ow)
    shapes = [data_s, off, w] + ([] if no_bias else [(num_filter,)])
    return shapes, [(data_s[0], num_filter, oh, ow)], []


@register("_contrib_DeformableConvolution", arg_names=_dconv_args,
          aliases=["DeformableConvolution"], infer_shape=_dconv_infer_shape)
def _deformable_convolution(ins, attrs, ctx):
    """Deformable convolution v1 (``deformable_convolution-inl.h:58`` via
    ``nn/deformable_im2col.cuh``): bilinear-sample the input at each
    kernel tap displaced by the learned offsets (offset channels per
    deformable group: [dy, dx] interleaved over taps), then one dense
    grouped matmul with the weights — im2col product on the MXU.
    Out-of-map corners contribute zero, matching the reference's
    ``im2col_bilinear`` zero-padding."""
    data, offset, weight = ins[0], ins[1], ins[2]
    bias = ins[3] if len(ins) > 3 else None
    n, cin, height, width = data.shape
    kernel = parse_tuple(attrs.get("kernel"), 2)
    stride = parse_tuple(attrs.get("stride") or (1, 1), 2)
    pad = parse_tuple(attrs.get("pad") or (0, 0), 2)
    dilate = parse_tuple(attrs.get("dilate") or (1, 1), 2)
    num_group = parse_int(attrs.get("num_group"), 1)
    dg = parse_int(attrs.get("num_deformable_group"), 1)
    kh, kw = kernel
    oh = (height + 2 * pad[0] - (dilate[0] * (kh - 1) + 1)) // stride[0] + 1
    ow = (width + 2 * pad[1] - (dilate[1] * (kw - 1) + 1)) // stride[1] + 1

    oy = jnp.arange(oh) * stride[0] - pad[0]
    ox = jnp.arange(ow) * stride[1] - pad[1]
    ky = jnp.arange(kh) * dilate[0]
    kx = jnp.arange(kw) * dilate[1]
    base_y = jnp.broadcast_to(
        oy[None, None, :, None] + ky[:, None, None, None],
        (kh, kw, oh, ow)).astype(jnp.float32)
    base_x = jnp.broadcast_to(
        ox[None, None, None, :] + kx[None, :, None, None],
        (kh, kw, oh, ow)).astype(jnp.float32)

    off = offset.reshape(n, dg, kh * kw, 2, oh, ow)
    sy = base_y[None, None] + off[:, :, :, 0].reshape(n, dg, kh, kw, oh, ow)
    sx = base_x[None, None] + off[:, :, :, 1].reshape(n, dg, kh, kw, oh, ow)
    cpg_d = cin // dg

    def sample_image(img, sy_i, sx_i):
        """img (C, H, W); sy/sx (dg, kh, kw, oh, ow) →
        (C, kh, kw, oh, ow) bilinear samples.  Exact
        ``deformable_im2col`` semantics: a sample is zero unless its
        coordinate is in [0, size) — (-1, 0) fringe contributes NOTHING
        — and the last fractional row/column snaps to the edge pixel
        with full weight (the h_low >= height-1 clamp resets lh to 0)."""

        def per_dgroup(img_g, yy, xx):
            y = yy.reshape(-1)
            x = xx.reshape(-1)
            inside = (y >= 0.0) & (y < height) & (x >= 0.0) & (x < width)
            y0 = jnp.floor(y)
            x0 = jnp.floor(x)
            snap_y = y0 >= height - 1
            snap_x = x0 >= width - 1
            y0 = jnp.where(snap_y, height - 1.0, y0)
            x0 = jnp.where(snap_x, width - 1.0, x0)
            y1 = jnp.where(snap_y, height - 1.0, y0 + 1)
            x1 = jnp.where(snap_x, width - 1.0, x0 + 1)
            wy = jnp.where(snap_y, 0.0, y - y0)
            wx = jnp.where(snap_x, 0.0, x - x0)

            def at(yi, xi):
                return img_g[:, jnp.clip(yi, 0, height - 1
                                         ).astype(jnp.int32),
                             jnp.clip(xi, 0, width - 1).astype(jnp.int32)]

            v = (at(y0, x0) * (1 - wy) * (1 - wx)
                 + at(y0, x1) * (1 - wy) * wx
                 + at(y1, x0) * wy * (1 - wx)
                 + at(y1, x1) * wy * wx)
            v = v * inside[None, :]
            return v.reshape((img_g.shape[0],) + yy.shape)

        groups = img.reshape(dg, cpg_d, height, width)
        out = jax.vmap(per_dgroup)(groups, sy_i, sx_i)
        return out.reshape(cin, kh, kw, oh, ow)

    cols = jax.vmap(sample_image)(data.astype(jnp.float32), sy, sx)
    cpg = cin // num_group
    fpg = weight.shape[0] // num_group
    cols_g = cols.reshape(n, num_group, cpg * kh * kw, oh * ow)
    w_g = weight.astype(jnp.float32).reshape(num_group, fpg, cpg * kh * kw)
    y = jnp.einsum("ngkp,gfk->ngfp", cols_g, w_g)
    y = y.reshape(n, weight.shape[0], oh, ow)
    if bias is not None:
        y = y + bias.astype(y.dtype).reshape(1, -1, 1, 1)
    return y.astype(data.dtype)


# ---------------------------------------------------------------------------
# krprod — row-wise Khatri-Rao product
# ---------------------------------------------------------------------------

@register("_contrib_krprod", arg_names=None, aliases=["khatri_rao"])
def _krprod(ins, attrs, ctx):
    """Row-wise Khatri-Rao product (``krprod.h:49`` row_wise_kronecker):
    out[i] = kron(A[i], B[i], ...) for matrices sharing a row count."""
    out = ins[0]
    for m in ins[1:]:
        r = out.shape[0]
        out = (out[:, :, None] * m[:, None, :]).reshape(r, -1)
    return out
