"""Fused optimizer-update ops.

Reference analog: ``src/operator/tensor/optimizer_op.cc`` — sgd_update,
sgd_mom_update, adam_update, rmsprop_update etc. run *as engine ops* so the
whole update is one fused kernel.  Here each is one jax-traceable function;
inside a pjit train step XLA fuses it with the gradient all-reduce epilogue.

All follow the reference update math including ``rescale_grad``,
``clip_gradient`` and ``wd`` (weight decay applied to the *gradient*).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, parse_float

__all__ = []


def _prep_grad(grad, weight, attrs):
    rescale = parse_float(attrs.get("rescale_grad", 1.0))
    clip = parse_float(attrs.get("clip_gradient", -1.0))
    wd = parse_float(attrs.get("wd", 0.0))
    g = grad * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g + wd * weight


@register("sgd_update", arg_names=["weight", "grad"], mutate_inputs=[0])
def _sgd_update(ins, attrs, ctx):
    weight, grad = ins
    lr = parse_float(attrs.get("lr"))
    g = _prep_grad(grad, weight, attrs)
    return weight - lr * g


@register("sgd_mom_update", arg_names=["weight", "grad", "mom"],
          mutate_inputs=[0, 2], num_outputs=2)
def _sgd_mom_update(ins, attrs, ctx):
    weight, grad, mom = ins
    lr = parse_float(attrs.get("lr"))
    momentum = parse_float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, weight, attrs)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", arg_names=["weight", "grad", "mom"],
          mutate_inputs=[0, 2], num_outputs=2)
def _nag_mom_update(ins, attrs, ctx):
    weight, grad, mom = ins
    lr = parse_float(attrs.get("lr"))
    momentum = parse_float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, weight, attrs)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", arg_names=["weight", "grad", "mean", "var"],
          mutate_inputs=[0, 2, 3], num_outputs=3)
def _adam_update(ins, attrs, ctx):
    weight, grad, mean, var = ins
    lr = parse_float(attrs.get("lr"))
    beta1 = parse_float(attrs.get("beta1", 0.9))
    beta2 = parse_float(attrs.get("beta2", 0.999))
    eps = parse_float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, weight, attrs)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + eps)
    return new_w, new_mean, new_var


@register("rmsprop_update", arg_names=["weight", "grad", "n"],
          mutate_inputs=[0, 2], num_outputs=2)
def _rmsprop_update(ins, attrs, ctx):
    weight, grad, n = ins
    lr = parse_float(attrs.get("lr"))
    gamma1 = parse_float(attrs.get("gamma1", 0.95))
    eps = parse_float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, weight, attrs)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    return weight - lr * g / jnp.sqrt(new_n + eps), new_n


@register("rmspropalex_update",
          arg_names=["weight", "grad", "n", "g", "delta"],
          mutate_inputs=[0, 2, 3, 4], num_outputs=4)
def _rmspropalex_update(ins, attrs, ctx):
    weight, grad, n, gbar, delta = ins
    lr = parse_float(attrs.get("lr"))
    gamma1 = parse_float(attrs.get("gamma1", 0.95))
    gamma2 = parse_float(attrs.get("gamma2", 0.9))
    eps = parse_float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, weight, attrs)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * gbar
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_g) + eps)
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", arg_names=["weight", "grad", "z", "n"],
          mutate_inputs=[0, 2, 3], num_outputs=3)
def _ftrl_update(ins, attrs, ctx):
    weight, grad, z, n = ins
    lr = parse_float(attrs.get("lr"))
    lamda1 = parse_float(attrs.get("lamda1", 0.01))
    beta = parse_float(attrs.get("beta", 1.0))
    wd = parse_float(attrs.get("wd", 0.0))
    rescale = parse_float(attrs.get("rescale_grad", 1.0))
    clip = parse_float(attrs.get("clip_gradient", -1.0))
    g = grad * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    new_z = z + g - (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr * weight
    new_n = n + jnp.square(g)
    new_w = (jnp.sign(new_z) * lamda1 - new_z) / \
        ((beta + jnp.sqrt(new_n)) / lr + wd) * (jnp.abs(new_z) > lamda1)
    return new_w, new_z, new_n
