"""Indexing ops: Embedding, take, one_hot, gather/scatter.

Reference analog: ``src/operator/tensor/indexing_op.{h,cc,cu}``.  Gathers map
onto XLA ``gather`` which TPU executes natively; no ``AddTakeGrad`` custom
kernel needed — ``jax.vjp`` of ``take`` emits the scatter-add.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, parse_int, parse_bool, parse_float, parse_tuple

__all__ = []


def _embedding_infer_shape(in_shapes, attrs):
    data_s, weight_s = in_shapes
    input_dim = parse_int(attrs.get("input_dim"))
    output_dim = parse_int(attrs.get("output_dim"))
    if weight_s is None:
        weight_s = (input_dim, output_dim)
    out_s = None if data_s is None else tuple(data_s) + (output_dim,)
    return [data_s, weight_s], [out_s], []


@register("Embedding", arg_names=["data", "weight"],
          infer_shape=_embedding_infer_shape)
def _embedding(ins, attrs, ctx):
    """Embedding lookup (``src/operator/tensor/indexing_op.h`` Embedding).
    Weight shape back-inferred from (input_dim, output_dim) for
    simple_bind parity."""
    data, weight = ins
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("take", arg_names=["a", "indices"])
def _take(ins, attrs, ctx):
    a, indices = ins
    axis = parse_int(attrs.get("axis"), 0)
    mode = attrs.get("mode", "clip")
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", arg_names=["a", "indices"])
def _batch_take(ins, attrs, ctx):
    a, indices = ins
    rows = jnp.arange(a.shape[0])
    return a[rows, indices.astype(jnp.int32)]


@register("one_hot", arg_names=["indices"])
def _one_hot(ins, attrs, ctx):
    depth = parse_int(attrs.get("depth"))
    on = parse_float(attrs.get("on_value", 1.0))
    off = parse_float(attrs.get("off_value", 0.0))
    from ..base import dtype_np

    dt = dtype_np(attrs.get("dtype", "float32"))
    oh = jax.nn.one_hot(ins[0].astype(jnp.int32), depth)
    return (oh * (on - off) + off).astype(dt)


@register("gather_nd", arg_names=["data", "indices"])
def _gather_nd(ins, attrs, ctx):
    data, indices = ins
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", arg_names=["data", "indices"])
def _scatter_nd(ins, attrs, ctx):
    data, indices = ins
    shape = parse_tuple(attrs.get("shape"))
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd", arg_names=["lhs", "rhs", "indices"])
def _scatter_set_nd(ins, attrs, ctx):
    lhs, rhs, indices = ins
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register("pick", arg_names=["data", "index"])
def _pick(ins, attrs, ctx):
    """Pick per-row elements along an axis
    (``src/operator/tensor/broadcast_reduce_op.h`` pick)."""
    data, index = ins
    axis = parse_int(attrs.get("axis"), -1)
    keepdims = parse_bool(attrs.get("keepdims", False))
    ax = axis % data.ndim
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[ax] - 1)
    idx_exp = jnp.expand_dims(idx.reshape(
        tuple(data.shape[i] for i in range(data.ndim) if i != ax)), ax)
    out = jnp.take_along_axis(data, idx_exp, axis=ax)
    if not keepdims:
        out = jnp.squeeze(out, axis=ax)
    return out


@register("where", arg_names=["condition", "x", "y"])
def _where(ins, attrs, ctx):
    """``src/operator/tensor/control_flow_op.h`` where: condition may be
    same-shape or a vector over axis 0."""
    cond, x, y = ins
    if cond.shape != x.shape and cond.ndim == 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond != 0, x, y)
