"""Sampling ops (``src/operator/random/sample_op.{h,cc,cu}`` +
``multisample_op``): uniform/normal/gamma/exponential/poisson/neg-binomial,
plus multinomial and shuffle.

TPU-native RNG: ops receive a jax PRNG key via OpContext (the analog of the
reference's per-device ``ResourceRequest::kRandom`` PRNG seeded by
``mx.random.seed``, ``src/resource.cc:145``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import dtype_np
from .registry import register, parse_tuple, parse_float, parse_int, parse_bool

__all__ = []


def _shape_dtype(attrs, default_dtype="float32"):
    shape = parse_tuple(attrs.get("shape") or (1,))
    dt = dtype_np(attrs.get("dtype") or default_dtype)
    return shape, dt


@register("_random_uniform", arg_names=[], needs_rng=True,
          aliases=["uniform", "random_uniform"])
def _uniform(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    low = parse_float(attrs.get("low", 0.0))
    high = parse_float(attrs.get("high", 1.0))
    return jax.random.uniform(ctx.rng, shape, dtype=dt, minval=low,
                              maxval=high)


@register("_random_normal", arg_names=[], needs_rng=True,
          aliases=["normal", "random_normal"])
def _normal(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    loc = parse_float(attrs.get("loc", 0.0))
    scale = parse_float(attrs.get("scale", 1.0))
    return jax.random.normal(ctx.rng, shape, dtype=dt) * scale + loc


@register("_random_gamma", arg_names=[], needs_rng=True,
          aliases=["random_gamma"])
def _gamma(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    alpha = parse_float(attrs.get("alpha", 1.0))
    beta = parse_float(attrs.get("beta", 1.0))
    return jax.random.gamma(ctx.rng, alpha, shape, dtype=dt) * beta


@register("_random_exponential", arg_names=[], needs_rng=True,
          aliases=["random_exponential"])
def _exponential(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    lam = parse_float(attrs.get("lam", 1.0))
    return jax.random.exponential(ctx.rng, shape, dtype=dt) / lam


@register("_random_poisson", arg_names=[], needs_rng=True,
          aliases=["random_poisson"])
def _poisson(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    lam = parse_float(attrs.get("lam", 1.0))
    return jax.random.poisson(ctx.rng, lam, shape).astype(dt)


@register("_random_negative_binomial", arg_names=[], needs_rng=True,
          aliases=["random_negative_binomial"])
def _neg_binomial(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    k = parse_int(attrs.get("k", 1))
    p = parse_float(attrs.get("p", 1.0))
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    g = jax.random.gamma(ctx.rng, k, shape) * ((1 - p) / p)
    return jax.random.poisson(jax.random.fold_in(ctx.rng, 1), g, shape
                              ).astype(dt)


@register("_random_generalized_negative_binomial", arg_names=[],
          needs_rng=True, aliases=["random_generalized_negative_binomial"])
def _gen_neg_binomial(ins, attrs, ctx):
    shape, dt = _shape_dtype(attrs)
    mu = parse_float(attrs.get("mu", 1.0))
    alpha = parse_float(attrs.get("alpha", 1.0))
    r = 1.0 / alpha
    p = mu / (mu + r)
    g = jax.random.gamma(ctx.rng, r, shape) * (p / (1 - p))
    return jax.random.poisson(jax.random.fold_in(ctx.rng, 1), g, shape
                              ).astype(dt)


# -- parameterized sampling with per-element distribution params ------------

def _sample_elemwise(name, sampler):
    @register(name, arg_names=None, needs_rng=True)
    def _f(ins, attrs, ctx, _s=sampler):
        shape = attrs.get("shape")
        shape = parse_tuple(shape) if shape not in (None, "", ()) else ()
        return _s(ctx.rng, ins, tuple(ins[0].shape) + tuple(shape))
    return _f


_sample_elemwise("sample_uniform",
                 lambda k, ins, s: ins[0].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim))
                 + jax.random.uniform(k, s) * (ins[1] - ins[0]).reshape(
                     ins[0].shape + (1,) * (len(s) - ins[0].ndim)))
_sample_elemwise("sample_normal",
                 lambda k, ins, s: ins[0].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim))
                 + jax.random.normal(k, s) * ins[1].reshape(
                     ins[0].shape + (1,) * (len(s) - ins[0].ndim)))
_sample_elemwise("sample_gamma",
                 lambda k, ins, s: jax.random.gamma(
                     k, ins[0].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim)), s)
                 * ins[1].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim)))
_sample_elemwise("sample_exponential",
                 lambda k, ins, s: jax.random.exponential(k, s)
                 / ins[0].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim)))
_sample_elemwise("sample_poisson",
                 lambda k, ins, s: jax.random.poisson(
                     k, ins[0].reshape(ins[0].shape + (1,) * (len(s) - ins[0].ndim)), s
                 ).astype(jnp.float32))


@register("_sample_multinomial", arg_names=["data"], needs_rng=True,
          aliases=["sample_multinomial"])
def _multinomial(ins, attrs, ctx):
    """Sample class indices from (batched) probability rows
    (``src/operator/random/multisample_op``)."""
    p = ins[0]
    shape = attrs.get("shape")
    n = 1 if shape in (None, "", ()) else int(parse_tuple(shape)[0])
    logits = jnp.log(jnp.maximum(p, 1e-37))
    if p.ndim == 1:
        out = jax.random.categorical(ctx.rng, logits, shape=(n,))
        return out.astype(jnp.float32)
    out = jax.random.categorical(ctx.rng, logits[:, None, :], axis=-1,
                                 shape=(p.shape[0], n))
    return (out if n > 1 else out[:, 0]).astype(jnp.float32)


@register("_shuffle", arg_names=["data"], needs_rng=True, aliases=["shuffle"])
def _shuffle(ins, attrs, ctx):
    return jax.random.permutation(ctx.rng, ins[0], axis=0)
