"""Linear-algebra ops (``src/operator/tensor/la_op.{h,cc}`` backed by LAPACK
via ``c_lapack_api.h`` in the reference; here backed by
``jax.numpy.linalg``/``jax.scipy.linalg`` which lower to XLA custom calls)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register, parse_bool, parse_float

__all__ = []


@register("_linalg_gemm", arg_names=["A", "B", "C"], aliases=["linalg_gemm"])
def _gemm(ins, attrs, ctx):
    a, b, c = ins
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    alpha = parse_float(attrs.get("alpha", 1.0))
    beta = parse_float(attrs.get("beta", 1.0))
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register("_linalg_gemm2", arg_names=["A", "B"], aliases=["linalg_gemm2"])
def _gemm2(ins, attrs, ctx):
    a, b = ins
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    alpha = parse_float(attrs.get("alpha", 1.0))
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("_linalg_potrf", arg_names=["A"], aliases=["linalg_potrf"])
def _potrf(ins, attrs, ctx):
    return jnp.linalg.cholesky(ins[0])


@register("_linalg_potri", arg_names=["A"], aliases=["linalg_potri"])
def _potri(ins, attrs, ctx):
    # inverse from cholesky factor L: (L Lᵀ)⁻¹
    l = ins[0]
    inv_l = jsl.solve_triangular(l, jnp.broadcast_to(
        jnp.eye(l.shape[-1], dtype=l.dtype), l.shape), lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@register("_linalg_trmm", arg_names=["A", "B"], aliases=["linalg_trmm"])
def _trmm(ins, attrs, ctx):
    a, b = ins
    transpose = parse_bool(attrs.get("transpose", False))
    rightside = parse_bool(attrs.get("rightside", False))
    alpha = parse_float(attrs.get("alpha", 1.0))
    at = jnp.swapaxes(a, -1, -2) if transpose else a
    return alpha * (jnp.matmul(b, at) if rightside else jnp.matmul(at, b))


@register("_linalg_trsm", arg_names=["A", "B"], aliases=["linalg_trsm"])
def _trsm(ins, attrs, ctx):
    a, b = ins
    transpose = parse_bool(attrs.get("transpose", False))
    rightside = parse_bool(attrs.get("rightside", False))
    alpha = parse_float(attrs.get("alpha", 1.0))
    if rightside:
        # B · A⁻ᵀ' : solve Aᵀ' Xᵀ = Bᵀ with the *lower* factor A; transposing
        # the system flips the requested transpose flag
        sol = jsl.solve_triangular(a, jnp.swapaxes(b, -1, -2), lower=True,
                                   trans=0 if transpose else 1)
        return alpha * jnp.swapaxes(sol, -1, -2)
    return alpha * jsl.solve_triangular(a, b, lower=True,
                                        trans=1 if transpose else 0)


@register("_linalg_sumlogdiag", arg_names=["A"], aliases=["linalg_sumlogdiag"])
def _sumlogdiag(ins, attrs, ctx):
    a = ins[0]
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("_linalg_syrk", arg_names=["A"], aliases=["linalg_syrk"])
def _syrk(ins, attrs, ctx):
    a = ins[0]
    transpose = parse_bool(attrs.get("transpose", False))
    alpha = parse_float(attrs.get("alpha", 1.0))
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("_linalg_gelqf", arg_names=["A"], aliases=["linalg_gelqf"],
          num_outputs=2)
def _gelqf(ins, attrs, ctx):
    # LQ factorization: A = L Q with Q orthonormal rows
    q, r = jnp.linalg.qr(jnp.swapaxes(ins[0], -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
