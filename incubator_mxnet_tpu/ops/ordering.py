"""Ordering ops: sort / argsort / topk
(``src/operator/tensor/ordering_op*``, CUB/Thrust kernels in the reference —
XLA ``sort``/``top_k`` on TPU)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, parse_int, parse_bool

__all__ = []


def _axis_of(attrs, default=-1):
    a = attrs.get("axis", default)
    if a in (None, "None", ""):
        return None
    return parse_int(a)


@register("sort", arg_names=["data"])
def _sort(ins, attrs, ctx):
    x = ins[0]
    axis = _axis_of(attrs)
    is_ascend = parse_bool(attrs.get("is_ascend", True))
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    out = jnp.sort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", arg_names=["data"])
def _argsort(ins, attrs, ctx):
    x = ins[0]
    axis = _axis_of(attrs)
    is_ascend = parse_bool(attrs.get("is_ascend", True))
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


@register("topk", arg_names=["data"],
          num_outputs=lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1)
def _topk(ins, attrs, ctx):
    """top-k along axis; ret_typ in {value, indices, mask, both}
    (``ordering_op-inl.h`` semantics)."""
    x = ins[0]
    axis = _axis_of(attrs)
    k = parse_int(attrs.get("k"), 1)
    ret_typ = attrs.get("ret_typ", "indices")
    is_ascend = parse_bool(attrs.get("is_ascend", False))
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    ax = axis % x.ndim
    xs = jnp.moveaxis(x, ax, -1)
    if is_ascend:
        vals, idx = jax.lax.top_k(-xs, k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(xs, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(jnp.float32)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idx, ax, -1).astype(jnp.int32),
                            x.shape[ax]).sum(axis=-2)
        return jnp.moveaxis(oh, -1, ax)
    # reference kReturnBoth order is (values, indices)
    return (vals, idx) if ret_typ == "both" else vals
