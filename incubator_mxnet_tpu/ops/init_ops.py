"""Creation ops (``src/operator/tensor/init_op.{h,cc}``): zeros/ones/arange…"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import dtype_np
from .registry import register, parse_tuple, parse_float, parse_int

__all__ = []


def _creation_shape_dtype(attrs):
    shape = parse_tuple(attrs.get("shape"))
    dt = dtype_np(attrs.get("dtype", "float32"))
    return shape, dt


@register("_zeros", arg_names=[], aliases=["zeros"])
def _zeros(ins, attrs, ctx):
    shape, dt = _creation_shape_dtype(attrs)
    return jnp.zeros(shape, dtype=dt)


@register("_ones", arg_names=[], aliases=["ones"])
def _ones(ins, attrs, ctx):
    shape, dt = _creation_shape_dtype(attrs)
    return jnp.ones(shape, dtype=dt)


@register("_full", arg_names=[], aliases=["full"])
def _full(ins, attrs, ctx):
    shape, dt = _creation_shape_dtype(attrs)
    return jnp.full(shape, parse_float(attrs.get("value")), dtype=dt)


@register("_arange", arg_names=[], aliases=["arange"])
def _arange(ins, attrs, ctx):
    start = parse_float(attrs.get("start", 0.0))
    stop = attrs.get("stop")
    stop = None if stop in (None, "None", "") else parse_float(stop)
    step = parse_float(attrs.get("step", 1.0))
    repeat = parse_int(attrs.get("repeat"), 1)
    dt = dtype_np(attrs.get("dtype", "float32"))
    if stop is None:
        start, stop = 0.0, start
    out = jnp.arange(start, stop, step, dtype=dt)
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", arg_names=[], aliases=["eye"])
def _eye(ins, attrs, ctx):
    n = parse_int(attrs.get("N"))
    m = attrs.get("M")
    m = n if m in (None, "", "0", 0) else parse_int(m)
    k = parse_int(attrs.get("k"), 0)
    return jnp.eye(n, m, k=k, dtype=dtype_np(attrs.get("dtype", "float32")))
