"""Contrib ops (``src/operator/contrib/*``): detection + misc.

Round-1 subset: quantization helpers, CTC loss, count_sketch analog, and the
SSD MultiBox family + ROIPooling land with the detection stack (stage 7 of
SURVEY.md §7); fft/ifft via jnp.fft.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, parse_float, parse_int, parse_tuple, parse_bool

__all__ = []


@register("_contrib_quantize", arg_names=["data", "min_range", "max_range"],
          num_outputs=3, aliases=["quantize"])
def _quantize(ins, attrs, ctx):
    data, mn, mx = ins
    # uint8 affine quantization (contrib/quantize-inl.h)
    scale = (mx - mn) / 255.0
    q = jnp.clip(jnp.round((data - mn) / scale), 0, 255).astype(jnp.uint8)
    return q, mn, mx


@register("_contrib_dequantize", arg_names=["data", "min_range", "max_range"],
          aliases=["dequantize"])
def _dequantize(ins, attrs, ctx):
    data, mn, mx = ins
    scale = (mx - mn) / 255.0
    return data.astype(jnp.float32) * scale + mn


@register("_contrib_fft", arg_names=["data"], aliases=["fft"])
def _fft(ins, attrs, ctx):
    x = ins[0]
    out = jnp.fft.fft(x, axis=-1)
    # reference packs complex as interleaved real/imag, doubling last dim
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register("_contrib_ifft", arg_names=["data"], aliases=["ifft"])
def _ifft(ins, attrs, ctx):
    x = ins[0]
    pairs = x.reshape(x.shape[:-1] + (x.shape[-1] // 2, 2))
    z = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(z, axis=-1).real.astype(x.dtype) * z.shape[-1]


@register("_contrib_count_sketch", arg_names=["data", "h", "s"],
          aliases=["count_sketch"])
def _count_sketch(ins, attrs, ctx):
    data, h, s = ins
    out_dim = parse_int(attrs.get("out_dim"))
    n = data.shape[0]
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros((n, out_dim), dtype=data.dtype)
    return out.at[:, idx].add(data * sign[None, :])


@register("_contrib_DotProductAttention",
          arg_names=["query", "key", "value"],
          aliases=["DotProductAttention"])
def _dot_product_attention(ins, attrs, ctx):
    """Multi-head scaled-dot-product attention over (B, H, S, D) inputs.

    Not in the reference (v0.11 predates attention); provided as the
    contrib building block of the transformer family.  Routes through
    :func:`parallel.sequence.attention`: the Pallas flash kernel on TPU
    for lane-aligned shapes, the materialized oracle elsewhere
    (``impl`` attr: auto|flash|xla).
    """
    from ..parallel.sequence import attention
    from .registry import parse_bool, parse_float

    q, k, v = ins
    causal = parse_bool(attrs.get("causal", False))
    scale = attrs.get("scale")
    scale = parse_float(scale) if scale is not None else None
    impl = attrs.get("impl", "auto")
    return attention(q, k, v, causal=causal, scale=scale, impl=impl)


@register("_contrib_MoEFFN",
          arg_names=["data", "gate_weight", "expert_w1", "expert_w2"],
          num_outputs=3, aliases=["MoEFFN"])
def _moe_ffn_op(ins, attrs, ctx):
    """Top-k gated mixture-of-experts FFN, global (pjit) semantics.

    ``data`` (..., d); ``gate_weight`` (E, d); ``expert_w1`` (E, h, d);
    ``expert_w2`` (E, d, h) — FullyConnected (out, in) convention per
    expert.  Attrs: ``top_k`` (2, renormalized GShard gates; 1 =
    Switch), ``capacity_factor`` (1.25; over-capacity assignments drop
    in token order).  Outputs: ``out`` (..., d); ``aux_loss`` () — the
    Switch/GShard load-balancing loss E·Σ f_e·P_e with f_e counted
    PRE-capacity (kept-only counting would let a collapsed router hide
    behind its own overflow); ``overflow`` () — dropped fraction.

    Not in the reference (v0.11 predates MoE; SURVEY §2.4 "absent EP").
    Written with dense/global ops so it trains through FusedTrainStep
    on ANY mesh: shard expert_w1/expert_w2 over an 'ep' axis via
    ``param_partition`` and the XLA SPMD partitioner keeps the expert
    einsums device-local, lowering the dispatch scatter/gather to
    collectives over ICI (the shard_map twin with EXPLICIT all_to_all
    is parallel/moe.py; this op is the model-building face).
    """
    import math

    from ._moe_routing import route, sparse_combine, sparse_dispatch

    x, gw, w1, w2 = ins
    E = w1.shape[0]
    k = min(parse_int(attrs.get("top_k", 2)), E)
    cf = parse_float(attrs.get("capacity_factor", 1.25))
    d = x.shape[-1]
    lead = x.shape[:-1]
    T = 1
    for s in lead:
        T *= int(s)
    xf = x.reshape(T, d)
    # gating in f32 regardless of activation dtype (tiny, and router
    # logits are numerically delicate)
    logits = xf.astype(jnp.float32) @ gw.astype(jnp.float32).T
    probs = jax.nn.softmax(logits, axis=-1)                  # (T, E)
    cap = max(int(math.ceil(cf * k * T / E)), 1)
    # THE shared GShard routing bookkeeping (ops/_moe_routing.py)
    gate_vals, flat_e, onehot, keep, safe_pos = route(probs, k, cap)
    dispatch = sparse_dispatch(xf, flat_e, keep, safe_pos, E, cap, k)

    h = jax.nn.relu(jnp.einsum("ecd,ehd->ech", dispatch,
                               w1.astype(x.dtype)))
    y = jnp.einsum("ech,edh->ecd", h, w2.astype(x.dtype))

    out = sparse_combine(y, flat_e, keep, safe_pos, gate_vals, k)
    out = out.reshape(tuple(lead) + (d,))

    routed = onehot.sum(0) / (T * k)                         # f_e
    aux = (E * jnp.sum(routed * probs.mean(0))).astype(jnp.float32)
    overflow = 1.0 - keep.astype(jnp.float32).mean()
    return out, aux, overflow
