"""Contrib ops (``src/operator/contrib/*``): detection + misc.

Round-1 subset: quantization helpers, CTC loss, count_sketch analog, and the
SSD MultiBox family + ROIPooling land with the detection stack (stage 7 of
SURVEY.md §7); fft/ifft via jnp.fft.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, parse_float, parse_int, parse_tuple, parse_bool

__all__ = []


@register("_contrib_quantize", arg_names=["data", "min_range", "max_range"],
          num_outputs=3, aliases=["quantize"])
def _quantize(ins, attrs, ctx):
    data, mn, mx = ins
    # uint8 affine quantization (contrib/quantize-inl.h)
    scale = (mx - mn) / 255.0
    q = jnp.clip(jnp.round((data - mn) / scale), 0, 255).astype(jnp.uint8)
    return q, mn, mx


@register("_contrib_dequantize", arg_names=["data", "min_range", "max_range"],
          aliases=["dequantize"])
def _dequantize(ins, attrs, ctx):
    data, mn, mx = ins
    scale = (mx - mn) / 255.0
    return data.astype(jnp.float32) * scale + mn


@register("_contrib_fft", arg_names=["data"], aliases=["fft"])
def _fft(ins, attrs, ctx):
    x = ins[0]
    out = jnp.fft.fft(x, axis=-1)
    # reference packs complex as interleaved real/imag, doubling last dim
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register("_contrib_ifft", arg_names=["data"], aliases=["ifft"])
def _ifft(ins, attrs, ctx):
    x = ins[0]
    pairs = x.reshape(x.shape[:-1] + (x.shape[-1] // 2, 2))
    z = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(z, axis=-1).real.astype(x.dtype) * z.shape[-1]


@register("_contrib_count_sketch", arg_names=["data", "h", "s"],
          aliases=["count_sketch"])
def _count_sketch(ins, attrs, ctx):
    data, h, s = ins
    out_dim = parse_int(attrs.get("out_dim"))
    n = data.shape[0]
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros((n, out_dim), dtype=data.dtype)
    return out.at[:, idx].add(data * sign[None, :])


@register("_contrib_DotProductAttention",
          arg_names=["query", "key", "value"],
          aliases=["DotProductAttention"])
def _dot_product_attention(ins, attrs, ctx):
    """Multi-head scaled-dot-product attention over (B, H, S, D) inputs.

    Not in the reference (v0.11 predates attention); provided as the
    contrib building block of the transformer family.  Routes through
    :func:`parallel.sequence.attention`: the Pallas flash kernel on TPU
    for lane-aligned shapes, the materialized oracle elsewhere
    (``impl`` attr: auto|flash|xla).
    """
    from ..parallel.sequence import attention
    from .registry import parse_bool, parse_float

    q, k, v = ins
    causal = parse_bool(attrs.get("causal", False))
    scale = attrs.get("scale")
    scale = parse_float(scale) if scale is not None else None
    impl = attrs.get("impl", "auto")
    return attention(q, k, v, causal=causal, scale=scale, impl=impl)
