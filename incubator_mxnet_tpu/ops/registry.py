"""Single operator registry feeding both frontends.

Reference analog: the NNVM ``Op`` registry with attribute maps
(``Op::GetAttr<FInferShape>`` etc., SURVEY.md layer 2) + the op attr types in
``include/mxnet/op_attr_types.h``.  TPU-native redesign: an op is a *pure
function* over jax arrays; autograd is ``jax.vjp`` of that function, shape
inference is either an explicit rule (needed for ``simple_bind``-style
back-inference of parameter shapes) or ``jax.eval_shape`` of the forward.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, Registry

__all__ = ["OpDef", "OpContext", "register", "get_op", "list_ops", "OPS"]

# case-sensitive: the reference distinguishes e.g. ``softmax`` (op) from
# ``Softmax`` (SoftmaxOutput alias), ``crop`` (slice alias) from ``Crop``
OPS = Registry("operator", case_sensitive=True)


@dataclasses.dataclass
class OpContext:
    """Per-invocation execution context (``OpContext`` at
    ``include/mxnet/op_attr_types.h:66``): train/test phase and an optional
    PRNG key for stochastic ops (the reference's ``ResourceRequest::kRandom``
    per-device PRNG, ``src/resource.cc:84-150``)."""

    is_train: bool = False
    rng: Any = None  # jax PRNG key, only set when op.needs_rng


@dataclasses.dataclass
class OpDef:
    """One operator.

    ``fn(inputs, attrs, op_ctx) -> outputs`` where ``inputs`` is a list of
    jax arrays ordered ``arg_names + aux_names`` and ``outputs`` a tuple of
    jax arrays; ops with aux state return ``(outputs, new_aux)`` instead.
    """

    name: str
    fn: Callable
    arg_names: Optional[List[str]] = None  # None → variadic (*args like add_n)
    aux_names: List[str] = dataclasses.field(default_factory=list)
    num_outputs: int = 1
    infer_shape: Optional[Callable] = None
    attr_parser: Optional[Callable[[Dict[str, str]], Dict[str, Any]]] = None
    needs_rng: bool = False
    # Reference-visible aliases (e.g. "Flatten" vs "flatten").
    aliases: List[str] = dataclasses.field(default_factory=list)
    # Grad of i-th input is accumulated into input (kAddTo-style fused update
    # ops set this to mutate weights in-place at the NDArray layer).
    mutate_inputs: List[int] = dataclasses.field(default_factory=list)
    # Human doc
    doc: str = ""

    @property
    def has_aux(self) -> bool:
        return bool(self.aux_names)

    def get_arg_names(self, attrs: Optional[Dict[str, Any]] = None):
        """Input names for this op; may depend on attrs (e.g. ``no_bias``
        removes ``bias``, mirroring ``OperatorProperty::ListArguments``)."""
        if callable(self.arg_names):
            return self.arg_names(attrs or {})
        return self.arg_names

    def get_num_outputs(self, attrs: Optional[Dict[str, Any]] = None) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs or {})
        if self.num_outputs == -1:
            a = attrs or {}
            if "num_outputs" in a:
                return parse_int(a["num_outputs"])
            return 1
        return self.num_outputs

    # ---- invocation helpers ---------------------------------------------
    def apply(self, inputs: Sequence[Any], attrs: Dict[str, Any],
              op_ctx: Optional[OpContext] = None):
        """Run forward, normalizing the output to (list_of_outputs, new_aux)."""
        op_ctx = op_ctx or OpContext()
        out = self.fn(list(inputs), dict(attrs), op_ctx)
        if self.has_aux:
            outs, new_aux = out
        else:
            outs, new_aux = out, ()
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return list(outs), list(new_aux)


def register(name: str, *, arg_names=None, aux_names=(), num_outputs=1,
             infer_shape=None, attr_parser=None, needs_rng=False,
             aliases=(), mutate_inputs=(), doc=""):
    """Decorator: register a forward function as an operator under ``name``
    (and any ``aliases``)."""

    def _wrap(fn):
        if callable(arg_names):
            _args = arg_names
        elif arg_names is not None:
            _args = list(arg_names)
        else:
            _args = None
        opdef = OpDef(
            name=name, fn=fn, arg_names=_args,
            aux_names=list(aux_names), num_outputs=num_outputs,
            infer_shape=infer_shape, attr_parser=attr_parser,
            needs_rng=needs_rng, aliases=list(aliases),
            mutate_inputs=list(mutate_inputs), doc=doc or fn.__doc__ or "")
        OPS.register(opdef, name=name)
        for a in opdef.aliases:
            OPS.register(opdef, name=a)
        return fn

    return _wrap


def get_op(name: str) -> OpDef:
    op = OPS.find(name)
    if op is None:
        raise MXNetError("operator '%s' is not registered" % name)
    return op


def list_ops() -> List[str]:
    return OPS.keys()


# ---------------------------------------------------------------------------
# attr coercion helpers (dmlc::Parameter-style typed parsing; SURVEY.md §5.6 —
# the frontend passes op attrs as strings, parsed once at op creation)
# ---------------------------------------------------------------------------


def parse_tuple(v, length=None, typ=int) -> Tuple:
    """Parse '(2, 2)' / '2' / (2, 2) into a tuple of ``typ``."""
    if v is None:
        return None
    if isinstance(v, str):
        v = v.strip()
        if v.startswith("(") or v.startswith("["):
            v = v[1:-1]
        parts = [p for p in v.replace(",", " ").split() if p]
        t = tuple(typ(float(p)) if typ is int else typ(p) for p in parts)
    elif isinstance(v, (tuple, list)):
        t = tuple(typ(x) for x in v)
    else:
        t = (typ(v),)
    if length is not None and len(t) == 1:
        t = t * length
    return t


def parse_bool(v) -> bool:
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return bool(v)


def parse_int(v, default=None):
    if v is None:
        return default
    return int(float(v)) if isinstance(v, str) else int(v)


def parse_float(v, default=None):
    if v is None:
        return default
    if isinstance(v, (str, int, float)):
        return float(v)
    try:
        import numpy as _np

        if isinstance(v, _np.generic):
            return float(v)
    except ImportError:
        pass
    # traced jax scalar (e.g. dynamic learning rate inside a jit step):
    # pass through — jnp arithmetic broadcasts it
    return v
