"""Fused RNN op.

Reference analog: the ``RNN`` operator (``src/operator/rnn-inl.h``) — in the
reference it is cuDNN-only (CPU ``CreateOperator`` is ``LOG(FATAL) << "Not
Implemented"``, rnn-inl.h:319; GPU at rnn.cu:29).  TPU-native redesign: one
``lax.scan`` per layer with the input projection hoisted out of the loop
(one big (T·N, I)×(I, G·H) matmul feeds the MXU; the scan body only does the
recurrent (N, H)×(H, G·H) matmul) — XLA compiles the whole stack into a
single fused loop.  Parameters use the cuDNN flat-vector packing the
reference exposes (all gate weights per layer/direction, then all biases),
so ``mx.sym.RNN`` checkpoints stay layout-compatible.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, parse_bool, parse_float, parse_int

__all__ = ["rnn_param_size", "rnn_pack_weights", "rnn_unpack_weights"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _layer_shapes(mode, num_layers, input_size, hidden, bidirectional):
    """Yield (W_i shape, W_h shape, b shape×2) per (layer, direction)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else hidden * d
        for _ in range(d):
            yield ((g * hidden, in_size), (g * hidden, hidden),
                   (g * hidden,), (g * hidden,))


def rnn_param_size(mode, num_layers, input_size, hidden,
                   bidirectional=False) -> int:
    total = 0
    for wi, wh, bi, bh in _layer_shapes(mode, num_layers, input_size,
                                        hidden, bidirectional):
        total += int(np.prod(wi)) + int(np.prod(wh)) + bi[0] + bh[0]
    return total


def rnn_unpack_weights(params, mode, num_layers, input_size, hidden,
                       bidirectional=False):
    """Flat vector → list of (W_i, W_h, b_i, b_h) per (layer, direction);
    cuDNN order: all weights first, then all biases."""
    shapes = list(_layer_shapes(mode, num_layers, input_size, hidden,
                                bidirectional))
    out = []
    pos = 0
    ws = []
    for wi, wh, _, _ in shapes:
        n = int(np.prod(wi))
        ws.append(params[pos:pos + n].reshape(wi))
        pos += n
        n = int(np.prod(wh))
        ws.append(params[pos:pos + n].reshape(wh))
        pos += n
    bs = []
    for _, _, bi, bh in shapes:
        bs.append(params[pos:pos + bi[0]])
        pos += bi[0]
        bs.append(params[pos:pos + bh[0]])
        pos += bh[0]
    for i in range(len(shapes)):
        out.append((ws[2 * i], ws[2 * i + 1], bs[2 * i], bs[2 * i + 1]))
    return out


def rnn_pack_weights(weights, mode=None):
    """Inverse of unpack: list of (W_i, W_h, b_i, b_h) → flat vector."""
    flat = [w for tup in weights for w in (tup[0].reshape(-1),
                                           tup[1].reshape(-1))]
    flat += [b for tup in weights for b in (tup[2], tup[3])]
    return jnp.concatenate(flat)


def _cell_step(mode, hidden):
    # NB: only b_i is hoisted into the input projection; b_h is applied
    # inside the step because cuDNN GRU places b_hn INSIDE the reset-gate
    # product: n = tanh(nx + b_in + r·(nh + b_hn))
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def step(carry, gates_x, wh, bh):
            (h,) = carry
            g = gates_x + jnp.matmul(h, wh.T) + bh
            h2 = act(g)
            return (h2,), h2

        return step, 1
    if mode == "lstm":
        def step(carry, gates_x, wh, bh):
            h, c = carry
            g = gates_x + jnp.matmul(h, wh.T) + bh
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            gg = jnp.tanh(gg)
            o = jax.nn.sigmoid(o)
            c2 = f * c + i * gg
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        return step, 2
    if mode == "gru":
        def step(carry, gates_x, wh, bh):
            (h,) = carry
            rx, zx, nx = jnp.split(gates_x, 3, axis=-1)
            gh = jnp.matmul(h, wh.T) + bh
            rh, zh, nh = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            n = jnp.tanh(nx + r * nh)
            h2 = (1 - z) * n + z * h
            return (h2,), h2

        return step, 1
    raise ValueError("unknown RNN mode %s" % mode)


def _run_direction(x, wi, wh, bi, bh, h0, c0, mode, hidden, reverse):
    """One (layer, direction) scan.  x: (T, N, I)."""
    step, n_state = _cell_step(mode, hidden)
    T, N, _ = x.shape
    # hoist the input projection out of the recurrence → one MXU matmul
    gates_x = jnp.matmul(x.reshape(T * N, -1), wi.T).reshape(T, N, -1) + bi
    if reverse:
        gates_x = jnp.flip(gates_x, axis=0)
    carry0 = (h0,) if n_state == 1 else (h0, c0)

    def body(carry, gx):
        return step(carry, gx, wh, bh)

    carry, ys = jax.lax.scan(body, carry0, gates_x)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, carry


def _rnn_impl(data, params, state_h, state_c, attrs, ctx):
    mode = attrs.get("mode", "lstm")
    hidden = parse_int(attrs.get("state_size"))
    num_layers = parse_int(attrs.get("num_layers"), 1)
    bidirectional = parse_bool(attrs.get("bidirectional", False))
    p_drop = parse_float(attrs.get("p", 0.0))
    d = 2 if bidirectional else 1
    input_size = data.shape[2]

    weights = rnn_unpack_weights(params, mode, num_layers, input_size,
                                 hidden, bidirectional)
    x = data
    out_h, out_c = [], []
    for layer in range(num_layers):
        ys = []
        for direction in range(d):
            idx = layer * d + direction
            wi, wh, bi, bh = weights[idx]
            h0 = state_h[idx]
            c0 = state_c[idx] if state_c is not None else None
            y, carry = _run_direction(x, wi, wh, bi, bh, h0, c0, mode,
                                      hidden, reverse=(direction == 1))
            ys.append(y)
            out_h.append(carry[0])
            if len(carry) > 1:
                out_c.append(carry[1])
        x = ys[0] if d == 1 else jnp.concatenate(ys, axis=-1)
        if p_drop > 0 and ctx.is_train and ctx.rng is not None \
                and layer < num_layers - 1:
            keep = 1.0 - p_drop
            mask = jax.random.bernoulli(
                jax.random.fold_in(ctx.rng, layer), keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0)
    return x, jnp.stack(out_h), (jnp.stack(out_c) if out_c else None)


def _rnn_args(attrs):
    if attrs.get("mode", "lstm") == "lstm":
        return ["data", "parameters", "state", "state_cell"]
    return ["data", "parameters", "state"]


def _rnn_infer_shape(in_shapes, attrs):
    mode = attrs.get("mode", "lstm")
    hidden = parse_int(attrs.get("state_size"))
    num_layers = parse_int(attrs.get("num_layers"), 1)
    bidirectional = parse_bool(attrs.get("bidirectional", False))
    state_outputs = parse_bool(attrs.get("state_outputs", False))
    d = 2 if bidirectional else 1
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    T, N, I = data_s
    pshape = (rnn_param_size(mode, num_layers, I, hidden, bidirectional),)
    sshape = (num_layers * d, N, hidden)
    shapes = [data_s, pshape, sshape]
    if mode == "lstm":
        shapes.append(sshape)
    outs = [(T, N, hidden * d)]
    if state_outputs:
        outs.append(sshape)
        if mode == "lstm":
            outs.append(sshape)
    return shapes, outs, []


def _rnn_num_outputs(attrs):
    if not parse_bool(attrs.get("state_outputs", False)):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN", arg_names=_rnn_args, infer_shape=_rnn_infer_shape,
          num_outputs=_rnn_num_outputs, needs_rng=True)
def _rnn(ins, attrs, ctx):
    data = ins[0]
    params = ins[1]
    state_h = ins[2]
    state_c = ins[3] if len(ins) > 3 else None
    out, hN, cN = _rnn_impl(data, params, state_h, state_c, attrs, ctx)
    if not parse_bool(attrs.get("state_outputs", False)):
        return out
    if cN is not None:
        return out, hN, cN
    return out, hN
