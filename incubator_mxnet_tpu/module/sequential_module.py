"""SequentialModule — a container that chains modules head-to-tail.

Reference analog: ``python/mxnet/module/sequential_module.py:28``.
Module ``i``'s outputs become module ``i+1``'s data; the iterator
labels are routed to whichever member was added with
``take_labels=True`` (typically the loss head).  Together with
:class:`~.python_module.PythonLossModule` this lets a python-side loss
ride behind a compiled Symbol module — see
``tests/test_module_variants.py`` and ``examples/train_stochastic_depth.py``.
"""
from __future__ import annotations

import copy
import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ["SequentialModule"]


def _require(ok, what):
    """State-ordering guard (bind → init_params → init_optimizer)."""
    if not ok:
        raise MXNetError("SequentialModule: %s" % what)


class SequentialModule(BaseModule):
    """A chain of :class:`BaseModule` members executed in order.

    ``add`` accepts two per-member options:

    * ``take_labels`` — this member receives the data iterator's
      labels at bind time (and feeds ``update_metric``).
    * ``auto_wiring`` — the previous member's output shapes are
      renamed to this member's ``data_names`` before binding, so the
      chain composes without hand-matching tensor names.
    """

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"
    _META_KEYS = frozenset((META_TAKE_LABELS, META_AUTO_WIRING))

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None

    def add(self, module, **kwargs):
        """Append ``module`` (returns ``self`` for chaining)."""
        bad = sorted(set(kwargs) - self._META_KEYS)
        if bad:
            raise MXNetError(
                "SequentialModule.add got unexpected option(s) %s; "
                "supported: %s" % (bad, sorted(self._META_KEYS)))
        self._modules.append(module)
        self._metas.append(kwargs)
        # growing the chain invalidates any previous bind/init
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ------------------------------------------------------------- shapes
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        _require(self.binded, "data_shapes requires bind")
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        _require(self.binded, "label_shapes requires bind")
        return self._label_shapes

    @property
    def output_shapes(self):
        _require(self.binded, "output_shapes requires bind")
        return self._modules[-1].output_shapes

    # ------------------------------------------------------------- params
    def get_params(self):
        _require(self.binded and self.params_initialized,
                 "get_params requires bind + init_params")
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        _require(self.binded, "init_params requires bind")
        for module in self._modules:
            # allow_missing=True per member: a chain-level param dict
            # only covers each member's slice of the names
            module.init_params(initializer=initializer,
                               arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=True,
                               force_init=force_init)
        self._check_duplicate_names()
        self.params_initialized = True

    def _check_duplicate_names(self):
        """Reject a chain whose members share a parameter name — the
        merged ``get_params`` dict would silently drop one of them."""
        owner = {}
        for i, module in enumerate(self._modules):
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                if name in owner:
                    raise MXNetError(
                        "duplicated parameter name '%s': member %d "
                        "(%s) reuses it from member %d (%s)" % (
                            name, i, type(module).__name__, owner[name],
                            type(self._modules[owner[name]]).__name__))
                owner[name] = i

    # ------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if inputs_need_grad and not for_training:
            raise MXNetError("inputs_need_grad requires for_training")
        if shared_module is not None:
            raise MXNetError(
                "SequentialModule does not support shared_module")
        _require(self._modules, "bind called on an empty chain")

        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        feed = data_shapes
        label_taken = False
        for i, (module, meta) in enumerate(zip(self._modules,
                                               self._metas)):
            takes = bool(meta.get(self.META_TAKE_LABELS, False))
            label_taken = label_taken or takes
            if meta.get(self.META_AUTO_WIRING, False):
                names = module.data_names
                if len(names) != len(feed):
                    raise MXNetError(
                        "auto_wiring: member %d expects %d inputs, "
                        "previous member produces %d" % (
                            i, len(names), len(feed)))
                feed = [(name, shape[1])
                        for name, shape in zip(names, feed)]
            module.bind(
                data_shapes=feed,
                label_shapes=label_shapes if takes else None,
                for_training=for_training,
                # interior members always need input grads to keep the
                # backward chain flowing; the head only on request
                inputs_need_grad=bool(for_training and
                                      (inputs_need_grad or i > 0)),
                force_rebind=force_rebind, shared_module=None,
                grad_req=grad_req)
            feed = module.output_shapes

        if not label_taken:
            self._label_shapes = None

    # ----------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        _require(self.binded and self.params_initialized,
                 "init_optimizer requires bind + init_params")
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # ----------------------------------------------------------- execution
    def forward(self, data_batch, is_train=None):
        _require(self.binded and self.params_initialized,
                 "forward requires bind + init_params")
        batch = copy.copy(data_batch)
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                return
            batch.data = module.get_outputs()
            if hasattr(batch, "provide_data"):
                batch.provide_data = [
                    (shape[0], out.shape) for shape, out in
                    zip(module.output_shapes, batch.data)]

    def backward(self, out_grads=None):
        _require(self.binded and self.params_initialized,
                 "backward requires bind + init_params")
        for i in reversed(range(len(self._modules))):
            self._modules[i].backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = self._modules[i].get_input_grads()

    def update(self):
        _require(self.binded and self.params_initialized and
                 self.optimizer_initialized,
                 "update requires bind + init_params + init_optimizer")
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        _require(self.binded and self.params_initialized,
                 "get_outputs requires bind + init_params")
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        _require(self.binded and self.params_initialized,
                 "get_input_grads requires bind + init_params")
        _require(self.inputs_need_grad,
                 "get_input_grads requires inputs_need_grad=True at bind")
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        _require(self.binded and self.params_initialized,
                 "update_metric requires bind + init_params")
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        _require(self.binded, "install_monitor requires bind")
        for module in self._modules:
            module.install_monitor(mon)
