"""DataParallelExecutorGroup — batch slicing over device contexts.

Reference analog: ``python/mxnet/module/executor_group.py:99`` —
``decide_slices`` splits each batch across contexts, binds one executor per
context sharing parameter memory, scatters inputs, gathers outputs.

TPU-native note: this classic per-device-executor path exists for API parity
and for CPU-context graph-partition tests; the high-throughput path on a TPU
mesh is the fused pjit step in :mod:`..parallel` (one program, batch sharded
by ``jax.sharding``), which Module selects automatically when all contexts
sit on one mesh.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..executor import Executor
from ..io import DataDesc
from ..ndarray import zeros as nd_zeros, concatenate as nd_concat
from ..ndarray.ndarray import NDArray

__all__ = ["DataParallelExecutorGroup", "_split_input_slice"]


def _split_input_slice(batch_size: int, work_load_list: Sequence[float]):
    """``executor_manager._split_input_slice``: slice indices per device."""
    total = sum(work_load_list)
    batch_num_list = [round(batch_size * w / total)
                      for w in work_load_list]
    # fix rounding drift
    delta = batch_size - sum(batch_num_list)
    batch_num_list[0] += delta
    slices = []
    start = 0
    for n in batch_num_list:
        slices.append(slice(start, start + int(n)))
        start += int(n)
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts: List[Context], workload,
                 data_shapes, label_shapes, param_names,
                 for_training: bool, inputs_need_grad: bool = False,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.logger = logger

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        self.data_shapes = [DataDesc(*d) if not isinstance(d, DataDesc)
                            else d for d in data_shapes]
        self.label_shapes = [DataDesc(*d) if not isinstance(d, DataDesc)
                             else d for d in (label_shapes or [])]
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [d.name for d in self.label_shapes]

        self.batch_size = self.data_shapes[0].shape[0]
        self.slices = _split_input_slice(self.batch_size, self.workload)

        if isinstance(grad_req, str):
            self.grad_req = {}
            for name in self.arg_names:
                if name in self.param_names:
                    self.grad_req[name] = ("null" if name in
                                           self.fixed_param_names or
                                           not for_training else grad_req)
                elif name in self.data_names:
                    self.grad_req[name] = grad_req if inputs_need_grad \
                        else "null"
                else:
                    self.grad_req[name] = "null"
        else:
            self.grad_req = dict(grad_req)

        self.execs: List[Executor] = []
        shared_execs = shared_group.execs if shared_group is not None \
            else [None] * len(contexts)
        for i, ctx in enumerate(contexts):
            shapes = {}
            n_i = self.slices[i].stop - self.slices[i].start
            for d in self.data_shapes:
                shapes[d.name] = (n_i,) + tuple(d.shape[1:])
            for l in self.label_shapes:
                shapes[l.name] = (n_i,) + tuple(l.shape[1:])
            self.execs.append(symbol.simple_bind(
                ctx=ctx, grad_req=self.grad_req,
                shared_exec=shared_execs[i], **shapes))

        # param arrays shared across calls: [n_params][n_devices]
        self.param_arrays = [[e.arg_dict[n] for e in self.execs]
                             for n in self.param_names]
        self.grad_arrays = [[e.grad_dict.get(n) for e in self.execs]
                            for n in self.param_names]
        self.aux_arrays = [[e.aux_dict[n] for e in self.execs]
                           for n in self.aux_names]
        self.data_arrays = [[e.arg_dict[n] for e in self.execs]
                            for n in self.data_names]
        self.input_grad_arrays = (
            [[e.grad_dict.get(n) for e in self.execs]
             for n in self.data_names] if inputs_need_grad else [])

    # ----------------------------------------------------------------- data
    def _scatter(self, arrays, names):
        for name, arr in zip(names, arrays):
            for i, (ex, sl) in enumerate(zip(self.execs, self.slices)):
                if name in ex.arg_dict:
                    piece = arr.data[sl] if isinstance(arr, NDArray) \
                        else np.asarray(arr)[sl]
                    ex._write_buf(ex.arg_dict[name], piece)

    def forward(self, data_batch, is_train: Optional[bool] = None) -> None:
        if is_train is None:
            is_train = self.for_training
        self._scatter(data_batch.data, self.data_names)
        if self.label_names and data_batch.label:
            self._scatter(data_batch.label, self.label_names)
        for ex in self.execs:
            ex.forward(is_train=is_train)

    def backward(self, out_grads=None) -> None:
        if not self.for_training:
            raise MXNetError("backward on a non-training executor group")
        for i, (ex, sl) in enumerate(zip(self.execs, self.slices)):
            og = None
            if out_grads is not None:
                og = [g[sl] if isinstance(g, NDArray)
                      else np.asarray(g)[sl] for g in out_grads]
            ex.backward(out_grads=og)

    def get_outputs(self, merge_multi_context: bool = True):
        outs = [[e.outputs[i] for e in self.execs]
                for i in range(len(self.output_names))]
        if merge_multi_context:
            return [o[0] if len(o) == 1 else nd_concat(o, axis=0)
                    for o in outs]
        return outs

    def get_output_arrays(self):
        """Merged outputs as RAW jax arrays — the overlapped train loop
        fences and accumulates metrics on these every step, so skip the
        per-call NDArray wrappers ``get_outputs`` allocates."""
        import jax
        import jax.numpy as jnp

        outs = []
        for i in range(len(self.output_names)):
            per_exec = [e.outputs[i].data for e in self.execs]
            if len(per_exec) > 1:
                # slices live on different contexts: gather onto the
                # first exec's device before the merge
                dev = next(iter(per_exec[0].devices()))
                per_exec = [jax.device_put(p, dev) for p in per_exec]
                outs.append(jnp.concatenate(per_exec, axis=0))
            else:
                outs.append(per_exec[0])
        return outs

    def get_input_grads(self, merge_multi_context: bool = True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        grads = self.input_grad_arrays
        if merge_multi_context:
            return [g[0] if len(g) == 1 else nd_concat(g, axis=0)
                    for g in grads]
        return grads

    # --------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params,
                   allow_extra: bool = False) -> None:
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=True)

    def get_params(self, arg_params, aux_params) -> None:
        """Average params across devices into the given dicts
        (reference semantics: weights are kept in sync, so take dev0 and
        divide-less copy; aux averaged)."""
        for name, blocks in zip(self.param_names, self.param_arrays):
            arg_params[name] = blocks[0].copy()
        for name, blocks in zip(self.aux_names, self.aux_arrays):
            if len(blocks) == 1:
                aux_params[name] = blocks[0].copy()
            else:
                acc = blocks[0].copy()
                for b in blocks[1:]:
                    acc += b.copyto(acc.context)
                aux_params[name] = acc / len(blocks)

    def update_metric(self, eval_metric, labels) -> None:
        for ex, sl in zip(self.execs, self.slices):
            labels_slice = [l[sl] if isinstance(l, NDArray)
                            else np.asarray(l)[sl] for l in labels]
            eval_metric.update(labels_slice, ex.outputs)

    def install_monitor(self, mon) -> None:
        for ex in self.execs:
            mon.install(ex)
