"""``mx.mod`` — Module training API (``python/mxnet/module/``)."""
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup
from .module import Module

__all__ = ["BaseModule", "Module", "DataParallelExecutorGroup"]
