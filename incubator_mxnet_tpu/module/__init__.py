"""``mx.mod`` — Module training API (``python/mxnet/module/``)."""
from .base_module import BaseModule
from .bucketing_module import BucketingModule
from .executor_group import DataParallelExecutorGroup
from .module import Module
from .python_module import PythonLossModule, PythonModule
from .sequential_module import SequentialModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PythonModule", "PythonLossModule", "DataParallelExecutorGroup"]
