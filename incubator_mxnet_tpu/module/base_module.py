"""BaseModule — the high-level training API contract.

Reference analog: ``python/mxnet/module/base_module.py`` (``fit`` at :376:
bind → init_params → init_optimizer → loop{forward_backward, update,
update_metric}); the call stack is SURVEY.md §3.1.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import metric as metric_mod
from .. import telemetry, tracing
from ..base import MXNetError
from ..initializer import Uniform
from ..model import BatchEndParam
from ..ndarray.ndarray import NDArray

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, list):
        return obj
    return [obj]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ---------------------------------------------------------------- sugar
    def forward_backward(self, data_batch) -> None:
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0):
        """Evaluate on eval_data (reference ``base_module.py`` score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad]
                       for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None,
                merge_batches=True, reset=True, always_output_list=False):
        """Run inference over an iterator (reference predict)."""
        from ..ndarray import concatenate as nd_concat

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError("inconsistent output count")
            output_list2 = [nd_concat([out[i] for out in output_list],
                                      axis=0)
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint_manager=None):
        """The training loop (reference ``base_module.py:376``).

        ``checkpoint_manager`` (or the ``TP_CKPT_DIR`` env family via
        ``resilience.CheckpointManager.from_env``) arms fault tolerance:
        the loop auto-resumes from the newest committed checkpoint
        (params, optimizer state, and the epoch/batch data cursor),
        saves every ``every_n_steps`` batches, and honors SIGTERM/SIGINT
        with a final synchronous save at the next step boundary (see
        docs/fault_tolerance.md for the resume contract)."""
        assert num_epoch is not None, "please specify num_epoch"

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # ---- fault tolerance (docs/fault_tolerance.md) ------------------
        from .. import resilience
        from ..resilience import faults as _faults

        _cm = checkpoint_manager
        if _cm is None:
            _cm = resilience.CheckpointManager.from_env()
        _global_step = 0
        _resume_nbatch = 0
        if _cm is not None:
            resilience.install_preemption_handler()
            _meta = _cm.restore_latest(self)
            if _meta is not None:
                _extra = _meta.get("extra", {})
                begin_epoch = int(_extra.get("epoch", begin_epoch))
                _resume_nbatch = int(_extra.get("nbatch", 0))
                _global_step = int(_meta.get("step", 0))
                self.logger.info(
                    "Auto-resumed from checkpoint: epoch %d, batch %d "
                    "(global step %d)", begin_epoch, _resume_nbatch,
                    _global_step)

        # ---- overlap window (docs/input_pipeline.md) --------------------
        # TP_MAX_INFLIGHT>0 bounds dispatch via a ring of per-step fence
        # handles instead of the legacy per-batch host sync; 0 restores
        # the fully synchronous loop.  A monitor needs per-batch buffer
        # reads, so it forces sync mode.
        from ..base import get_env
        from ..overlap import InflightRing, fence_handle, max_inflight

        _max_if = max_inflight()
        _overlap = _max_if > 0 and monitor is None
        _ring = InflightRing(_max_if, scope="module") if _overlap else None
        # on-device metric accumulation replaces the per-batch
        # update_metric readback when the metric has a device twin; a
        # batch-end callback reads eval_metric every batch, so callbacks
        # keep the exact host path.  TP_DEVICE_METRICS=0 forces host.
        _dev_metric = None
        if _overlap and batch_end_callback is None \
                and get_env("DEVICE_METRICS", 1, int):
            _dev_metric = metric_mod.DeviceMetricAccumulator.create(
                eval_metric)
        _window = max(1, get_env("METRIC_WINDOW", 50, int))
        _outs_fn = getattr(self, "get_output_arrays", None)

        # sampled once per fit: telemetry can't toggle mid-training, and the
        # disabled loop must not pay even the enabled() call per step
        _tele = telemetry.enabled()
        # same contract for the flight recorder: one per-step trace
        # (dispatch / input-wait here; fence, PS RPC, and checkpoint
        # spans land on it via tracing.train_context())
        _trace_on = tracing.enabled()
        _tctx = None
        if _tele:
            _step_fence = get_env("TELEMETRY_STEP_FENCE", False, bool)
            _step_hist = telemetry.histogram("step_latency_seconds")
            _steps_ctr = telemetry.counter("steps_total")
            _samples_ctr = telemetry.counter("samples_total")
            _sps_gauge = telemetry.gauge("samples_per_sec")
            _epochs_ctr = telemetry.counter("epochs_total")

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            try:
                next_data_batch = next(data_iter)
            except StopIteration:
                # iterator arrived exhausted (e.g. a score() ran between
                # fits) — reset once and retry
                train_data.reset()
                data_iter = iter(train_data)
                next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if _resume_nbatch > 0:
                    # auto-resume replay: advance the data cursor to the
                    # checkpointed batch without computing, so the resumed
                    # stream matches the uninterrupted run batch for batch
                    _resume_nbatch -= 1
                    nbatch += 1
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
                    continue
                if monitor is not None:
                    monitor.tic()
                if _tele:
                    _t0 = time.monotonic()
                if _trace_on:
                    _tctx = tracing.start_trace(
                        "train.step", {"step": _global_step + 1,
                                       "epoch": epoch})
                    tracing.set_train_context(_tctx)
                    _tr0 = time.monotonic()
                self.forward_backward(data_batch)
                self.update()
                if _trace_on:
                    # host dispatch of the step program (device time
                    # surfaces later, at the ring fence)
                    tracing.record(_tctx, "train.dispatch", _tr0,
                                   time.monotonic())
                _outs = None
                if _ring is not None or _dev_metric is not None:
                    # the cached step outputs: raw jax arrays when the
                    # module exposes them (no NDArray wrap per step)
                    _outs = _outs_fn() if _outs_fn is not None else \
                        [o.data for o in self.get_outputs()]
                if _dev_metric is not None and data_batch.label:
                    # per-step partials accumulate in a donated device
                    # buffer; ONE readback per window instead of per batch
                    _dev_metric.update(data_batch.label, _outs)
                    if _dev_metric.pending >= _window:
                        _dev_metric.drain()
                else:
                    # legacy per-batch host path: every update is a
                    # device->host metric synchronization, counted so
                    # the bench A/B shows O(steps) vs O(steps/window)
                    self.update_metric(eval_metric, data_batch.label)
                    if _tele:
                        telemetry.counter("metric_readbacks_total").inc()
                if _tele:
                    if _step_fence:
                        # true readback fence: host-read one scalar so the
                        # latency sample covers device execution, not just
                        # async dispatch (block_until_ready is unreliable
                        # on some platforms — see PERF.md)
                        try:
                            outs = self.get_outputs()
                            if outs:
                                np.asarray(outs[0].data).ravel()[:1]
                        except Exception:
                            pass
                    _dt = time.monotonic() - _t0
                    _step_hist.observe(_dt)
                    _steps_ctr.inc()
                    _shape = getattr(data_batch.data[0], "shape",
                                     ()) if data_batch.data else ()
                    _bs = _shape[0] if _shape else 0
                    if _bs:
                        _samples_ctr.inc(_bs)
                        _sps_gauge.set(_bs / max(_dt, 1e-9))
                if _trace_on:
                    _tr0 = time.monotonic()
                try:
                    next_data_batch = next(data_iter)
                    self.prepare(next_data_batch)
                except StopIteration:
                    end_of_batch = True
                if _trace_on:
                    tracing.record(_tctx, "train.input_wait", _tr0,
                                   time.monotonic())
                if _ring is not None:
                    # admit this step into the in-flight window; fences
                    # the step TP_MAX_INFLIGHT behind (PERF.md true fence)
                    _ring.push(fence_handle(_outs[0]) if _outs else None)
                if monitor is not None:
                    monitor.toc_print()
                # nbatch counts COMPLETED batches when the callback runs
                # (the old post-callback increment reported the previous
                # count, skewing Speedometer's first window)
                nbatch += 1
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                # ---- step boundary: fault hook + checkpoint cadence ----
                _global_step += 1
                _faults.inject("step", step=_global_step)
                if _cm is not None:
                    _due = resilience.preemption_requested() or (
                        _cm.every_n_steps > 0
                        and _global_step % _cm.every_n_steps == 0)
                    if _due and _ring is not None:
                        # fence in-flight steps before the host snapshot
                        _ring.drain()
                    if _cm.step_end(self, _global_step,
                                    extra={"epoch": epoch,
                                           "nbatch": nbatch}):
                        if _dev_metric is not None:
                            _dev_metric.drain()
                        if _ring is not None:
                            _ring.drain()
                        if _trace_on:
                            tracing.set_train_context(None)
                            tracing.end_trace(_tctx)
                            tracing.flush()
                        self.logger.info(
                            "Preemption checkpoint committed at step %d "
                            "— exiting fit cleanly", _global_step)
                        return
                if _trace_on:
                    # step boundary: close this step's trace (tail
                    # sampling decides whether it is kept)
                    tracing.set_train_context(None)
                    tracing.end_trace(_tctx)

            if _dev_metric is not None:
                _dev_metric.drain()  # fold the tail window before logging
            if _ring is not None:
                _ring.drain()  # epoch boundary: everything executed
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if _tele:
                _epochs_ctr.inc()
                telemetry.flush()
            if _trace_on:
                tracing.flush()  # epoch boundary: persist kept traces

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # ------------------------------------------------------- to be provided
    @property
    def symbol(self):
        return self._symbol

    def prepare(self, data_batch):
        pass

    def get_params(self):
        raise NotImplementedError

    def init_params(self, *a, **k):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname: str) -> None:
        from ..ndarray import save as nd_save

        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd_save(fname, save_dict)

    def load_params(self, fname: str) -> None:
        from ..ndarray import load as nd_load

        save_dict = nd_load(fname)
        arg_params, aux_params = {}, {}
        for k, v in save_dict.items():
            tp, name = k.split(":", 1)
            if tp == "arg":
                arg_params[name] = v
            elif tp == "aux":
                aux_params[name] = v
        self.set_params(arg_params, aux_params)

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, *a, **k):
        raise NotImplementedError

    def init_optimizer(self, *a, **k):
        raise NotImplementedError

    def install_monitor(self, mon):
        raise NotImplementedError
