"""Module — Symbol + data-parallel executor group + optimizer.

Reference analog: ``python/mxnet/module/module.py`` (bind :351,
init_optimizer :461, update :615) per the SURVEY.md §3.1 call stack.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import Context, cpu
from ..initializer import InitDesc, Uniform
from ..io import DataDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from ..ndarray import zeros as nd_zeros
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        if context is None:
            context = [cpu()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list or [1] * len(context)

        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group: Optional[DataParallelExecutorGroup] = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------------ loading
    @staticmethod
    def load(prefix: str, epoch: int, load_optimizer_states=False,
             **kwargs) -> "Module":
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix: str, epoch: int,
                        save_optimizer_states: bool = False) -> None:
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params,
                        self._aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    # ------------------------------------------------------------- shapes
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.get_outputs()
        return list(zip(self._output_names, [o.shape for o in outs]))

    # ------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = [l if isinstance(l, DataDesc) else DataDesc(*l)
                              for l in (label_shapes or [])]

        shared_group = shared_module._exec_group if shared_module else None
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req)
        if self.params_initialized:
            # params were loaded before bind (Module.load path): push them
            # into the freshly bound executors
            self._exec_group.set_params(self._arg_params, self._aux_params)
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    # -------------------------------------------------------------- params
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"

        # master copies live on the FIRST EXECUTOR's device: created on
        # the default device they would drag every set_params through
        # the cross-device path (~5 MB/s D2H on the tunneled chip —
        # measured 22 s for ResNet-50's 100 MB)
        master_ctx = self._context[0]
        if self._arg_params is None:
            self._arg_params = {
                n: nd_zeros(shape, ctx=master_ctx, dtype=arr.dtype)
                for n, shape, arr in (
                    (n, blocks[0].shape, blocks[0])
                    for n, blocks in zip(self._param_names,
                                         self._exec_group.param_arrays))}
        if self._aux_params is None:
            self._aux_params = {
                n: nd_zeros(blocks[0].shape, ctx=master_ctx)
                for n, blocks in zip(self._aux_names,
                                     self._exec_group.aux_arrays)}

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr[:] = cache_arr.asnumpy() \
                        if isinstance(cache_arr, NDArray) else cache_arr
            elif cache is not None and not allow_missing:
                raise MXNetError("%s not found in provided params" % name)
            elif initializer is not None:
                # per-variable __init__ attrs override the global
                # initializer (mx.sym.Variable(init=...))
                initializer(InitDesc(name, attrs.get(name, {})), arr)

        for name, arr in sorted(self._arg_params.items()):
            desc_cache = arg_params if arg_params else None
            _impl(name, arr, desc_cache)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params if aux_params else None)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def get_params(self):
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        if self._params_dirty and self._exec_group is not None:
            self._exec_group.get_params(self._arg_params, self._aux_params)
            self._params_dirty = False

    # ----------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized")
            return

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and \
                "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k1, ctx in enumerate(self._context):
                idx2name.update({i * len(self._context) + k1: n
                                 for i, n in enumerate(
                                     self._exec_group.param_names)})

        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt_mod.create(optimizer, sym=self.symbol,
                                       param_idx2name=idx2name,
                                       **optimizer_params)
        else:
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "optimizer rescale_grad != 1/batch_size (%s vs %s)",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # ----------------------------------------------------------- execution
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """push/pull through kvstore or local updater
        (reference ``module.py:615`` → ``model.py:106``)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore,
                                      self._exec_group.param_names)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._exec_group.param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_output_arrays(self):
        """Merged step outputs as raw jax arrays (no NDArray wrap) —
        the overlapped ``fit`` fence/metric path (executor_group)."""
        assert self.binded and self.params_initialized
        return self._exec_group.get_output_arrays()

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def borrow_optimizer(self, shared_module: "Module") -> None:
        """Share optimizer/kvstore/updater state with another Module bound
        over the same params (reference ``module.py`` borrow_optimizer;
        used by BucketingModule so every bucket steps the same state)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # -------------------------------------------------------- opt states
    def save_optimizer_states(self, fname: str):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname: str):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        # pull live device weights back before rebinding, else the rebound
        # executors would restart from the stale host-side copies
        self._sync_params_from_devices()
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)
