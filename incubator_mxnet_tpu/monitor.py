"""Monitor — per-tensor stats over executor outputs every N batches
(``python/mxnet/monitor.py`` + executor monitor callback,
``graph_executor.cc:1209-1229``)."""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional

from . import telemetry
from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        if stat_func is None:
            def stat_func(x):
                import numpy as np

                return np.abs(x.asnumpy()).mean()

        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List = []
        self.step = 0
        self.exes: List = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def install(self, exe) -> None:
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List:
        if not self.activated:
            return []
        self.activated = False
        res = self.queue
        self.queue = []
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        if telemetry.enabled():
            for _, name, value in res:
                try:
                    telemetry.gauge("monitor_stat",
                                    {"tensor": name}).set(float(value))
                except (TypeError, ValueError):
                    pass  # stat_func may return non-scalar stats
        return res

    def toc_print(self) -> None:
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, str(v))
