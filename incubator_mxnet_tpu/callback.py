"""Training callbacks (``python/mxnet/callback.py``): Speedometer,
do_checkpoint, module_checkpoint, ProgressBar, LogValidationMetricsCallback."""
from __future__ import annotations

import logging
import math
import time

from . import telemetry

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "ProgressBar", "LogValidationMetricsCallback"]


class Speedometer:
    """Log samples/sec every `frequent` batches (reference
    ``callback.py`` Speedometer)."""

    def __init__(self, batch_size: int, frequent: int = 50,
                 auto_reset: bool = True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0.0
        self.last_count = 0
        self.last_tick = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                # monotonic: wall-clock steps (NTP, DST) must not yield
                # negative elapsed; clamp avoids ZeroDivisionError when two
                # callbacks land within timer resolution
                elapsed = time.monotonic() - self.tic
                # exact window: batches completed since the previous
                # tick (fit reports nbatch as the completed-batch count,
                # so the delta is right even on the first window — the
                # old `frequent * batch_size` overcounted it)
                n = max(count - self.last_tick, 1)
                speed = n * self.batch_size / max(elapsed, 1e-9)
                telemetry.gauge("speedometer_samples_per_sec").set(speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "	".join("%s=%f" % nv for nv in name_value)
                    logging.info(
                        "Epoch[%d] Batch [%d]	Speed: %.2f samples/sec	%s",
                        param.epoch, count, speed, msg)
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]	Speed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.monotonic()
                self.last_tick = count
        else:
            self.init = True
            self.tic = time.monotonic()
            self.last_tick = count


def do_checkpoint(prefix: str, period: int = 1):
    """Epoch checkpoint callback (reference ``callback.py:55``).

    Files land atomically (``model.save_checkpoint`` writes a temp file
    then renames), so a crash mid-save never corrupts the previous epoch's
    checkpoint.  For step-granular async checkpointing with auto-resume,
    use :class:`incubator_mxnet_tpu.resilience.CheckpointManager` instead.
    """
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        from .model import save_checkpoint

        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
            telemetry.counter("ckpt_saves_total",
                              {"mode": "epoch"}).inc()

    return _callback


def module_checkpoint(mod, prefix: str, period: int = 1,
                      save_optimizer_states: bool = False):
    """Module-level checkpoint callback (reference ``callback.py:27``).
    Same atomic-write guarantee as :func:`do_checkpoint`."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
            telemetry.counter("ckpt_saves_total",
                              {"mode": "epoch"}).inc()

    return _callback


class ProgressBar:
    def __init__(self, total: int, length: int = 80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
