"""Profiler — per-op stats and Chrome ``chrome://tracing`` JSON dump.

Reference analog: ``src/engine/profiler.{h,cc}`` (``Profiler``,
``OprExecStat``, ``EmitEvent``) + ``python/mxnet/profiler.py``
(``profiler_set_config`` / ``profiler_set_state``) + the atexit dump wired
in ``src/initialize.cc:57-66``.

TPU-native design: two complementary capture layers share one trace file —

1. **Engine-level op events** via the ``Engine`` profile hook (the analog of
   ``ExecuteOprBlock``'s ``OprExecStat`` capture,
   ``src/engine/threaded_engine.h:312-325``).  These are host-side dispatch
   spans; on TPU the device work is asynchronous, so these measure the
   python-visible cost exactly the way the reference's engine measured
   worker-thread spans.
2. **XLA/device traces** via ``jax.profiler`` (``start_trace``/
   ``stop_trace`` → TensorBoard/XPlane) for true on-device timing — the
   TPU replacement for per-kernel CUDA timing.

Env controls (reference ``docs/how_to/env_var.md:99-107``):
``TP_PROFILER_AUTOSTART=1`` starts profiling at import and dumps at exit;
``TP_PROFILER_MODE`` ∈ {``symbolic``, ``all``} (``MXNET_PROFILER_MODE``);
``TP_PROFILER_FILENAME`` overrides the output path.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import List, Optional

from .base import get_env

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "pause", "resume", "Scope", "record_counter", "record_async",
           "start_xla_trace", "stop_xla_trace"]

_lock = threading.Lock()


class _Event:
    __slots__ = ("name", "t0", "t1", "tid", "cat")

    def __init__(self, name, t0, t1, tid, cat):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.cat = cat


class _Profiler:
    """Singleton state (``Profiler::Get()``)."""

    def __init__(self):
        self.mode = get_env("PROFILER_MODE", "symbolic") or "symbolic"
        self.filename = get_env("PROFILER_FILENAME", "profile.json")
        self.running = False
        self.events: List[_Event] = []
        # (name, value, t) triples from the telemetry registry — NOT gated
        # on ``running``: the metrics layer decides when to publish, the
        # trace is just one of its exposition formats
        self.counters: List[tuple] = []
        # (name, id, t0, t1, cat, args) async spans from the tracing
        # layer — like counters, NOT gated on ``running``: the flight
        # recorder owns its own sampling, the trace file is just one of
        # its exposition formats
        self.asyncs: List[tuple] = []
        self._hook_installed = False
        self._epoch = time.perf_counter()

    def now_us(self, t: float) -> float:
        return (t - self._epoch) * 1e6

    def record(self, name: str, t0: float, t1: float,
               cat: str = "operator") -> None:
        if not self.running:
            return
        ev = _Event(name, t0, t1, threading.get_ident(), cat)
        with _lock:
            self.events.append(ev)

    def install_hook(self) -> None:
        if self._hook_installed:
            return
        from .engine import engine

        engine().add_profile_hook(self._on_op)
        self._hook_installed = True

    def _on_op(self, name: str, t0: float, t1: float) -> None:
        # MXNET_PROFILER_MODE=symbolic excludes imperative engine ops
        # (env_var.md:99-107); the engine hook only sees imperative ops
        # here (symbolic work is inside jitted programs)
        if self.mode == "symbolic":
            return
        self.record(name, t0, t1)

    def record_counter(self, name: str, value: float,
                       t: Optional[float] = None) -> None:
        """Append a Chrome counter sample (``"ph": "C"``) — the shared-
        timeline exposition for telemetry counters/gauges."""
        t = time.perf_counter() if t is None else t
        with _lock:
            self.counters.append((name, float(value), t))

    def record_async(self, name: str, aid: str, t0: float, t1: float,
                     cat: str = "trace", args=None) -> None:
        """Append one async span — dumped as a Chrome ``"b"``/``"e"``
        pair keyed by ``aid`` so all spans of one distributed trace
        render as a single async track."""
        with _lock:
            self.asyncs.append((name, aid, t0, t1, cat, args))

    def dump(self, fname: Optional[str] = None) -> str:
        """Write accumulated events as Chrome trace-event JSON
        (``Profiler::DumpProfile`` / ``EmitEvent``, profiler.h:75-148)."""
        fname = fname or self.filename
        with _lock:
            events = list(self.events)
            counters = list(self.counters)
            asyncs = list(self.asyncs)
        traces = []
        # process-name metadata, like EmitPid
        tids = sorted({e.tid for e in events})
        for i, tid in enumerate(tids):
            traces.append({"ph": "M", "args": {"name": "engine thread %d"
                                               % i},
                           "pid": 0, "tid": tid,
                           "name": "thread_name"})
        for e in events:
            traces.append({
                "name": e.name, "cat": e.cat, "ph": "B",
                "ts": self.now_us(e.t0), "pid": 0, "tid": e.tid,
            })
            traces.append({
                "name": e.name, "cat": e.cat, "ph": "E",
                "ts": self.now_us(e.t1), "pid": 0, "tid": e.tid,
            })
        for name, value, t in counters:
            traces.append({
                "name": name, "cat": "telemetry", "ph": "C",
                "ts": self.now_us(t), "pid": 0, "tid": 0,
                "args": {"value": value},
            })
        for name, aid, t0, t1, cat, args in asyncs:
            traces.append({
                "name": name, "cat": cat, "ph": "b", "id": aid,
                "ts": self.now_us(t0), "pid": 0, "tid": 0,
                "args": args or {},
            })
            traces.append({
                # args repeated on the close half: consumers pair b/e
                # by (id, name, args.span_id)
                "name": name, "cat": cat, "ph": "e", "id": aid,
                "ts": self.now_us(t1), "pid": 0, "tid": 0,
                "args": args or {},
            })
        with open(fname, "w") as f:
            json.dump({"traceEvents": traces, "displayTimeUnit": "ms"}, f)
        return fname


_prof = _Profiler()


def profiler_set_config(mode: str = "symbolic",
                        filename: str = "profile.json") -> None:
    """``MXSetProfilerConfig`` analog."""
    _prof.mode = mode
    _prof.filename = filename


def profiler_set_state(state: str = "stop") -> None:
    """``MXSetProfilerState``: 'run' starts capture, 'stop' dumps."""
    if state in ("run", 1):
        with _lock:
            _prof.events = []  # fresh capture per run/stop session
            _prof.counters = []
            _prof.asyncs = []
        _prof.install_hook()
        _prof.running = True
    elif state in ("stop", 0):
        _prof.running = False
        _prof.dump()
    else:
        raise ValueError("state must be 'run' or 'stop'")


def pause() -> None:
    _prof.running = False


def resume() -> None:
    _prof.install_hook()
    _prof.running = True


def dump_profile(fname: Optional[str] = None) -> str:
    return _prof.dump(fname)


def record_counter(name: str, value: float,
                   t: Optional[float] = None) -> None:
    """Telemetry-facing entry: add one counter sample to the trace."""
    _prof.record_counter(name, value, t)


def record_async(name: str, aid: str, t0: float, t1: float,
                 cat: str = "trace", args=None) -> None:
    """Tracing-facing entry: add one async ``"b"``/``"e"`` span pair
    keyed by ``aid`` (perf_counter-epoch seconds)."""
    _prof.record_async(name, aid, t0, t1, cat, args)


class Scope:
    """Context manager recording a named span (python-side custom events —
    the analog of profiling a cached-op segment)."""

    def __init__(self, name: str, cat: str = "python"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _prof.record(self.name, self._t0, time.perf_counter(), self.cat)
        return False


# -- on-device XLA traces ----------------------------------------------------


def start_xla_trace(logdir: str = "/tmp/tp_xla_trace") -> None:
    """Start a jax/XLA device trace (TensorBoard XPlane format) — the TPU
    replacement for per-kernel CUDA timing."""
    import jax

    jax.profiler.start_trace(logdir)


def stop_xla_trace() -> None:
    import jax

    jax.profiler.stop_trace()


# -- autostart (initialize.cc:57-66 atexit dump) -----------------------------

if (os.environ.get("TP_PROFILER_AUTOSTART") or
        os.environ.get("MXNET_PROFILER_AUTOSTART")) == "1":
    resume()
    atexit.register(lambda: _prof.dump())
