"""Model symbol zoo.

Reference analog: ``example/image-classification/symbols/`` (lenet, mlp,
alexnet, vgg, resnet, inception-bn) — the networks behind every BASELINE
config.  Each ``get_symbol`` returns a ``SoftmaxOutput``-headed Symbol
exactly like the reference train scripts expect.
"""
from .lenet import get_symbol as lenet
from .mlp import get_symbol as mlp
from .alexnet import get_symbol as alexnet
from .resnet import get_symbol as resnet, image_data_shape
from .vgg import get_symbol as vgg
from .inception_bn import get_symbol as inception_bn
from .lstm_ptb import get_symbol as lstm_ptb, lstm_ptb_sym_gen
from .ssd import ssd_300, get_symbol_train as ssd_train, \
    get_symbol as ssd_deploy
from . import rcnn
from .transformer import get_symbol as transformer_lm
from . import dcgan

__all__ = ["lenet", "mlp", "alexnet", "resnet", "vgg", "inception_bn",
           "lstm_ptb", "lstm_ptb_sym_gen", "ssd_300", "ssd_train",
           "ssd_deploy", "transformer_lm", "get_symbol",
           "image_data_shape"]


_ZOO = {"lenet": lenet, "mlp": mlp, "alexnet": alexnet, "resnet": resnet,
        "vgg": vgg, "inception-bn": inception_bn,
        "inception_bn": inception_bn, "lstm_ptb": lstm_ptb,
        "ssd_300": ssd_300, "ssd": ssd_300,
        "transformer_lm": transformer_lm, "transformer": transformer_lm}


def get_symbol(network: str, **kwargs):
    if network.startswith("resnet"):
        depth = network[len("resnet"):]
        if depth.isdigit():
            kwargs.setdefault("num_layers", int(depth))
        return resnet(**kwargs)
    return _ZOO[network](**kwargs)
