"""Decoder-only transformer LM — the long-context flagship family.

Not in the reference (v0.11 predates attention; its sequence family is
the PTB LSTM, ``example/rnn``); included because long-context training
is first-class here.  Pre-norm GPT-style blocks over the contrib
attention op (``_contrib_DotProductAttention`` — Pallas flash kernel on
TPU for lane-aligned shapes); trains through the standard paths
(``Module.fit`` / ``FusedTrainStep``) like every other model family,
and the sequence axis shards across chips via
``parallel.sequence`` (ring/Ulysses) for contexts beyond one chip.
"""
from __future__ import annotations

from .. import symbol as sym


def _split_heads(x, batch, seq, heads, head_dim, name):
    # (B, S, E) → (B, H, S, D)
    # batch stays -1 so the symbol is BATCH-POLYMORPHIC: grad-accum
    # microbatches and pipeline stage bodies flow through without
    # rebuilding the graph
    r = sym.Reshape(x, shape=(-1, seq, heads, head_dim),
                    name=name + "_split")
    return sym.transpose(r, axes=(0, 2, 1, 3), name=name + "_bhsd")


def _merge_heads(x, batch, seq, embed, name):
    # (B, H, S, D) → (B, S, E)
    t = sym.transpose(x, axes=(0, 2, 1, 3), name=name + "_bshd")
    return sym.Reshape(t, shape=(-1, seq, embed), name=name + "_merge")


def _block(x, batch, seq, embed, heads, name, causal=True,
           attn_impl="auto", fused_qkv=False, moe_experts=0,
           moe_top_k=2, moe_capacity=1.25):
    head_dim = embed // heads
    ln1 = sym.LayerNorm(x, axis=-1, name=name + "_ln1")
    if fused_qkv:
        # one (3E, E) projection instead of three: fewer, larger MXU
        # calls (param name <block>_qkv_weight — not checkpoint-
        # compatible with the split form, hence opt-in)
        p3 = sym.FullyConnected(ln1, num_hidden=3 * embed,
                                flatten=False, no_bias=True,
                                name=name + "_qkv")
        qkv = []
        for i, part in enumerate(("q", "k", "v")):
            sl = sym.slice_axis(p3, axis=-1, begin=i * embed,
                                end=(i + 1) * embed,
                                name=name + "_" + part + "_slice")
            qkv.append(_split_heads(sl, batch, seq, heads, head_dim,
                                    name + "_" + part))
    else:
        qkv = []
        for part in ("q", "k", "v"):
            p = sym.FullyConnected(ln1, num_hidden=embed, flatten=False,
                                   no_bias=True, name=name + "_" + part)
            qkv.append(_split_heads(p, batch, seq, heads, head_dim,
                                    name + "_" + part))
    att = sym.DotProductAttention(*qkv, causal=causal, impl=attn_impl,
                                  name=name + "_attn")
    att = _merge_heads(att, batch, seq, embed, name + "_attn")
    proj = sym.FullyConnected(att, num_hidden=embed, flatten=False,
                              name=name + "_attn_proj")
    x = x + proj

    ln2 = sym.LayerNorm(x, axis=-1, name=name + "_ln2")
    if moe_experts:
        # mixture-of-experts FFN (round-4 verdict #3: MoE as a MODEL
        # capability, not just a parallel utility): explicit-shape
        # expert weights so infer_shape stays closed-form
        hdim = 4 * embed
        gate = sym.Variable(name + "_moe_gate_weight",
                            shape=(moe_experts, embed))
        # per-expert Glorot-uniform: the stacks are (E, out, in) — a
        # global Xavier would read dim 2+ as conv spatial dims and
        # scale by the full h·d fan, starting experts ~sqrt(E·h/2)×
        # too small at realistic widths
        import math

        from ..initializer import Uniform as _U

        expert_init = _U(math.sqrt(6.0 / (embed + hdim)))
        w1 = sym.Variable(name + "_moe_w1",
                          shape=(moe_experts, hdim, embed),
                          init=expert_init)
        w2 = sym.Variable(name + "_moe_w2",
                          shape=(moe_experts, embed, hdim),
                          init=expert_init)
        moe = sym.MoEFFN(ln2, gate, w1, w2, top_k=moe_top_k,
                         capacity_factor=moe_capacity,
                         name=name + "_moe")
        return x + moe[0], moe[1], moe[2]
    h = sym.FullyConnected(ln2, num_hidden=4 * embed, flatten=False,
                           name=name + "_ffn1")
    h = sym.Activation(h, act_type="relu", name=name + "_ffn_relu")
    h = sym.FullyConnected(h, num_hidden=embed, flatten=False,
                           name=name + "_ffn2")
    return x + h, None, None


def get_symbol(vocab_size=1000, embed=64, heads=4, num_layers=2,
               seq_len=64, batch_size=8, causal=True, dtype="float32",
               attn_impl="auto", head="softmax", fused_qkv=False,
               moe_experts=0, moe_top_k=2, moe_capacity=1.25,
               moe_aux_coeff=1e-2, **kwargs):
    """Decoder-only LM.  Inputs ``data`` (B, S) int tokens and
    ``softmax_label`` (B·S,) next-token targets.

    ``head='softmax'`` outputs per-position softmax over the vocabulary
    (``SoftmaxOutput`` semantics — O(B·S·V) output, fine for small V);
    ``head='fused'`` outputs the (B·S,) per-position cross-entropy loss
    through the chunked ``_contrib_SoftmaxXentHead``, which never
    materializes the (B·S, V) logits — the memory-safe configuration
    for large-vocab training (PERF.md §8c OOM analysis).

    Shapes are static (XLA contract) — batch/seq are build parameters,
    mirroring how ``BucketingModule`` handled variable length in the
    reference RNN family.

    ``moe_experts=E`` replaces every block's FFN with a top-k gated
    mixture of E experts (``_contrib_MoEFFN``): the symbol then has
    THREE outputs — [head, scaled aux loss, overflow (grad-blocked)].
    The aux term is the mean per-layer Switch/GShard balance loss
    scaled by ``moe_aux_coeff × B × S`` so its gradient pressure
    matches the SUMMED head loss and stays batch-size-invariant under
    the optimizer's ``rescale_grad=1/batch`` convention.  Train via
    ``FusedTrainStep`` with ``param_partition={*_moe_w1/w2: P('ep')}``
    for expert parallelism (see parallel/moe.py for the explicit-
    collective twin).
    """
    if embed % heads:
        raise ValueError("embed (%d) must divide by heads (%d)"
                         % (embed, heads))
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    tok = sym.Embedding(data, input_dim=vocab_size, output_dim=embed,
                        name="tok_embed")
    # learned positions: embed an arange via a constant-input trick is
    # graph-unfriendly; use a position Variable-free Embedding over
    # broadcast arange produced by the arange op
    pos_ids = sym.arange(start=0, stop=seq_len, dtype="int32",
                         name="pos_ids")
    pos = sym.Embedding(pos_ids, input_dim=seq_len, output_dim=embed,
                        name="pos_embed")
    x = sym.broadcast_add(tok, sym.Reshape(pos, shape=(1, seq_len, embed),
                                           name="pos_row"),
                          name="embed_sum")
    if dtype in ("float16", "bfloat16"):
        # bf16 activations (f32 masters stay f32 in FusedTrainStep);
        # logits cast back before the softmax, like the CNN families
        x = sym.Cast(x, dtype=dtype, name="to_lowp")
    auxes, overflows = [], []
    for i in range(num_layers):
        x, aux, over = _block(x, batch_size, seq_len, embed, heads,
                              "block%d" % i, causal=causal,
                              attn_impl=attn_impl, fused_qkv=fused_qkv,
                              moe_experts=moe_experts,
                              moe_top_k=moe_top_k,
                              moe_capacity=moe_capacity)
        if aux is not None:
            auxes.append(aux)
            overflows.append(over)
    x = sym.LayerNorm(x, axis=-1, name="ln_f")
    x = sym.Reshape(x, shape=(-1, embed), name="flatten_positions")
    # label comes in (B, S) like the PTB LSTM family and flattens to the
    # positions axis inside the graph (lstm_ptb.py:45 convention), so
    # Module's batch-axis slicing stays valid
    label_flat = sym.Reshape(label, shape=(-1,), name="label_flat")
    if head == "fused":
        w = sym.Variable("lm_head_weight")
        out = sym.SoftmaxXentHead(x, w, label_flat,
                                  num_hidden=vocab_size, name="softmax")
    else:
        logits = sym.FullyConnected(x, num_hidden=vocab_size,
                                    name="lm_head")
        if dtype in ("float16", "bfloat16"):
            logits = sym.Cast(logits, dtype="float32",
                              name="logits_f32")
        out = sym.SoftmaxOutput(logits, label_flat, name="softmax")
    if not auxes:
        return out
    aux_total = auxes[0]
    over_total = overflows[0]
    for a in auxes[1:]:
        aux_total = aux_total + a
    for o in overflows[1:]:
        over_total = over_total + o
    # summed-loss units: coeff × tokens × mean-layer aux (docstring).
    # The token count is computed at RUNTIME from the labels (not the
    # baked batch_size) so grad-accum microbatches scale correctly —
    # k microbatches each contribute coeff·(B/k)·S, summing to the
    # intended coeff·B·S
    tokens = sym.sum(sym.ones_like(label_flat), name="moe_tok_count")
    aux_scaled = aux_total * tokens * (moe_aux_coeff / num_layers)
    over_mean = sym.BlockGrad(over_total * (1.0 / num_layers),
                              name="moe_overflow")
    return sym.Group([out, aux_scaled, over_mean])
