"""Faster R-CNN (VGG16 backbone) — reference
``example/rcnn/rcnn/symbol/symbol_vgg.py`` (get_vgg_train :333,
get_vgg_test :263, get_vgg_rpn :178) and the python ``proposal_target``
custom op (``rcnn/io/rcnn.py`` sample_rois).

The RPN + Fast R-CNN head composition is symbol-level and uses the
framework's static-shape `_contrib_Proposal` / `ROIPooling` ops; the
train-time ROI sampler runs as a host CustomOp exactly like the
reference's default python path (``mx.symbol.Custom(op_type=
'proposal_target')``) — sampling is data-dependent control flow that
belongs on the host, not in XLA.
"""
from __future__ import annotations

import numpy as np

from .. import operator as op_mod
from .. import symbol as sym

NUM_ANCHORS = 9


def _vgg_conv(data):
    """VGG16 shared conv body (conv1_1..relu5_3, reference
    get_vgg_conv)."""
    x = data
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for block, (n, filt) in enumerate(cfg, start=1):
        for layer in range(1, n + 1):
            x = sym.Convolution(x, kernel=(3, 3), pad=(1, 1),
                                num_filter=filt,
                                name="conv%d_%d" % (block, layer))
            x = sym.Activation(x, act_type="relu",
                               name="relu%d_%d" % (block, layer))
        if block < 5:  # stride 16 total: conv5 is NOT followed by pool
            x = sym.Pooling(x, pool_type="max", kernel=(2, 2),
                            stride=(2, 2), name="pool%d" % block)
    return x


def _rpn_head(body, num_anchors):
    rpn_conv = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                               num_filter=512, name="rpn_conv_3x3")
    rpn_relu = sym.Activation(rpn_conv, act_type="relu", name="rpn_relu")
    cls_score = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                num_filter=2 * num_anchors,
                                name="rpn_cls_score")
    bbox_pred = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                num_filter=4 * num_anchors,
                                name="rpn_bbox_pred")
    return cls_score, bbox_pred


def _fast_rcnn_head(body, rois, num_classes, feat_stride):
    pool5 = sym.ROIPooling(body, rois, name="roi_pool5",
                           pooled_size=(7, 7),
                           spatial_scale=1.0 / feat_stride)
    flat = sym.Flatten(pool5, name="flatten")
    fc6 = sym.FullyConnected(flat, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu", name="relu6")
    drop6 = sym.Dropout(relu6, p=0.5, name="drop6")
    fc7 = sym.FullyConnected(drop6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu", name="relu7")
    drop7 = sym.Dropout(relu7, p=0.5, name="drop7")
    cls_score = sym.FullyConnected(drop7, num_hidden=num_classes,
                                   name="cls_score")
    bbox_pred = sym.FullyConnected(drop7, num_hidden=num_classes * 4,
                                   name="bbox_pred")
    return cls_score, bbox_pred


def _bbox_transform(ex, gt):
    """Box → regression-target parameterization (reference
    ``rcnn/processing/bbox_regression.py``)."""
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * (ew - 1.0)
    ecy = ex[:, 1] + 0.5 * (eh - 1.0)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt[:, 1] + 0.5 * (gh - 1.0)
    return np.stack([(gcx - ecx) / (ew + 1e-14),
                     (gcy - ecy) / (eh + 1e-14),
                     np.log(gw / ew), np.log(gh / eh)], axis=1)


def _overlaps(boxes, gt):
    """IoU matrix (N, M)."""
    ab = ((boxes[:, 2] - boxes[:, 0] + 1)
          * (boxes[:, 3] - boxes[:, 1] + 1))[:, None]
    ag = ((gt[:, 2] - gt[:, 0] + 1) * (gt[:, 3] - gt[:, 1] + 1))[None, :]
    iw = np.maximum(0, np.minimum(boxes[:, 2:3], gt[None, :, 2])
                    - np.maximum(boxes[:, 0:1], gt[None, :, 0]) + 1)
    ih = np.maximum(0, np.minimum(boxes[:, 3:4], gt[None, :, 3])
                    - np.maximum(boxes[:, 1:2], gt[None, :, 1]) + 1)
    inter = iw * ih
    return inter / (ab + ag - inter + 1e-14)


class ProposalTargetOp(op_mod.CustomOp):
    """Sample proposals into fixed-size ROI batches with labels and
    class-specific bbox targets (reference sample_rois)."""

    def __init__(self, num_classes, batch_rois, fg_fraction, fg_overlap):
        super().__init__()
        self.num_classes = num_classes
        self.batch_rois = batch_rois
        self.fg_rois = int(round(batch_rois * fg_fraction))
        self.fg_overlap = fg_overlap

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = np.asarray(in_data[0]).reshape(-1, 5)
        gt = np.asarray(in_data[1]).reshape(-1, 5)
        gt = gt[gt[:, 4] >= 0]  # -1-padded invalid rows
        n = self.batch_rois
        # include gt boxes as proposals (reference appends them)
        if len(gt):
            gt_rois = np.concatenate(
                [np.zeros((len(gt), 1), np.float32),
                 gt[:, :4].astype(np.float32)], axis=1)
            rois = np.concatenate([rois, gt_rois], axis=0)
        if len(gt):
            ov = _overlaps(rois[:, 1:5], gt[:, :4])
            gt_assign = ov.argmax(axis=1)
            max_ov = ov.max(axis=1)
        else:
            gt_assign = np.zeros(len(rois), np.int64)
            max_ov = np.zeros(len(rois), np.float32)

        fg = np.where(max_ov >= self.fg_overlap)[0]
        bg = np.where(max_ov < self.fg_overlap)[0]
        n_fg = min(self.fg_rois, len(fg))
        if len(fg) > n_fg:
            fg = np.random.choice(fg, n_fg, replace=False)
        else:
            fg = fg[:n_fg]
        n_bg = n - n_fg
        if len(bg) > 0:
            bg = np.random.choice(bg, n_bg, replace=len(bg) < n_bg)
            keep = np.concatenate([fg, bg])
        else:
            # every proposal is foreground: pad with foregrounds KEEPING
            # their labels — padding them as background would teach the
            # classifier that true object crops are background
            extra = np.random.choice(
                np.where(max_ov >= self.fg_overlap)[0], n_bg,
                replace=True)
            keep = np.concatenate([fg, extra])
            n_fg = n

        out_rois = rois[keep].astype(np.float32)
        labels = np.zeros(n, np.float32)
        targets = np.zeros((n, 4 * self.num_classes), np.float32)
        weights = np.zeros((n, 4 * self.num_classes), np.float32)
        if len(gt) and n_fg:
            cls = gt[gt_assign[keep[:n_fg]], 4].astype(np.int64)
            labels[:n_fg] = cls
            t = _bbox_transform(out_rois[:n_fg, 1:5],
                                gt[gt_assign[keep[:n_fg]], :4])
            for i, c in enumerate(cls):
                targets[i, 4 * c:4 * c + 4] = t[i]
                weights[i, 4 * c:4 * c + 4] = 1.0
        self.assign(out_data[0], req[0], out_rois)
        self.assign(out_data[1], req[1], labels)
        self.assign(out_data[2], req[2], targets)
        self.assign(out_data[3], req[3], weights)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i in range(len(in_grad)):
            self.assign(in_grad[i], req[i], 0)


@op_mod.register("proposal_target")
class ProposalTargetProp(op_mod.CustomOpProp):
    def __init__(self, num_classes, batch_images="1", batch_rois="128",
                 fg_fraction="0.25", fg_overlap="0.5"):
        super().__init__(need_top_grad=False)
        self.num_classes = int(num_classes)
        self.batch_rois = int(batch_rois)
        self.fg_fraction = float(fg_fraction)
        self.fg_overlap = float(fg_overlap)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_output", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        n = self.batch_rois
        return in_shape, [(n, 5), (n,), (n, 4 * self.num_classes),
                          (n, 4 * self.num_classes)], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return ProposalTargetOp(self.num_classes, self.batch_rois,
                                self.fg_fraction, self.fg_overlap)


def _proposal(cls_score_reshape, bbox_pred, im_info, num_anchors,
              feat_stride, pre_nms, post_nms, name="rois"):
    act = sym.SoftmaxActivation(cls_score_reshape, mode="channel",
                                name="rpn_cls_act")
    act_reshape = sym.Reshape(act, shape=(0, 2 * num_anchors, -1, 0),
                              name="rpn_cls_act_reshape")
    return getattr(sym, "_contrib_Proposal")(
        act_reshape, bbox_pred, im_info, name=name,
        feature_stride=feat_stride, scales=(8, 16, 32),
        ratios=(0.5, 1, 2), rpn_pre_nms_top_n=pre_nms,
        rpn_post_nms_top_n=post_nms, threshold=0.7, rpn_min_size=16)


def get_symbol_train(num_classes=21, num_anchors=NUM_ANCHORS,
                     feat_stride=16, batch_rois=128,
                     rpn_batch_size=256, pre_nms=6000, post_nms=300):
    """End-to-end Faster R-CNN training net (get_vgg_train :333)."""
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    gt_boxes = sym.Variable("gt_boxes")
    rpn_label = sym.Variable("label")
    rpn_bbox_target = sym.Variable("bbox_target")
    rpn_bbox_weight = sym.Variable("bbox_weight")

    body = _vgg_conv(data)
    rpn_cls_score, rpn_bbox_pred = _rpn_head(body, num_anchors)

    score_reshape = sym.Reshape(rpn_cls_score, shape=(0, 2, -1, 0),
                                name="rpn_cls_score_reshape")
    rpn_cls_prob = sym.SoftmaxOutput(
        score_reshape, rpn_label, multi_output=True,
        normalization="valid", use_ignore=True, ignore_label=-1,
        name="rpn_cls_prob")
    rpn_bbox_loss_ = rpn_bbox_weight * sym.smooth_l1(
        rpn_bbox_pred - rpn_bbox_target, scalar=3.0,
        name="rpn_bbox_loss_")
    rpn_bbox_loss = sym.MakeLoss(rpn_bbox_loss_, name="rpn_bbox_loss",
                                 grad_scale=1.0 / rpn_batch_size)

    rois = _proposal(score_reshape, rpn_bbox_pred, im_info, num_anchors,
                     feat_stride, pre_nms, post_nms)
    gt_reshape = sym.Reshape(gt_boxes, shape=(-1, 5),
                             name="gt_boxes_reshape")
    group = sym.Custom(rois, gt_reshape, op_type="proposal_target",
                       num_classes=num_classes, batch_rois=batch_rois,
                       name="proposal_target")
    rois, label, bbox_target, bbox_weight = \
        group[0], group[1], group[2], group[3]

    cls_score, bbox_pred = _fast_rcnn_head(body, rois, num_classes,
                                           feat_stride)
    cls_prob = sym.SoftmaxOutput(cls_score, label,
                                 normalization="batch", name="cls_prob")
    bbox_loss_ = bbox_weight * sym.smooth_l1(
        bbox_pred - bbox_target, scalar=1.0, name="bbox_loss_")
    bbox_loss = sym.MakeLoss(bbox_loss_, name="bbox_loss",
                             grad_scale=1.0 / batch_rois)
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss])


def get_symbol_test(num_classes=21, num_anchors=NUM_ANCHORS,
                    feat_stride=16, pre_nms=6000, post_nms=300,
                    batch_images=1):
    """Faster R-CNN inference net (get_vgg_test :263)."""
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    body = _vgg_conv(data)
    rpn_cls_score, rpn_bbox_pred = _rpn_head(body, num_anchors)
    score_reshape = sym.Reshape(rpn_cls_score, shape=(0, 2, -1, 0),
                                name="rpn_cls_score_reshape")
    rois = _proposal(score_reshape, rpn_bbox_pred, im_info, num_anchors,
                     feat_stride, pre_nms, post_nms)
    cls_score, bbox_pred = _fast_rcnn_head(body, rois, num_classes,
                                           feat_stride)
    cls_prob = sym.softmax(cls_score, name="cls_prob")
    cls_prob = sym.Reshape(cls_prob,
                           shape=(batch_images, -1, num_classes),
                           name="cls_prob_reshape")
    bbox_pred = sym.Reshape(bbox_pred,
                            shape=(batch_images, -1, 4 * num_classes),
                            name="bbox_pred_reshape")
    return sym.Group([rois, cls_prob, bbox_pred])


def get_symbol_rpn(num_anchors=NUM_ANCHORS, rpn_batch_size=256):
    """Stand-alone RPN training net (get_vgg_rpn :178)."""
    data = sym.Variable("data")
    rpn_label = sym.Variable("label")
    rpn_bbox_target = sym.Variable("bbox_target")
    rpn_bbox_weight = sym.Variable("bbox_weight")
    body = _vgg_conv(data)
    cls_score, bbox_pred = _rpn_head(body, num_anchors)
    score_reshape = sym.Reshape(cls_score, shape=(0, 2, -1, 0),
                                name="rpn_cls_score_reshape")
    cls_prob = sym.SoftmaxOutput(
        score_reshape, rpn_label, multi_output=True,
        normalization="valid", use_ignore=True, ignore_label=-1,
        name="rpn_cls_prob")
    bbox_loss_ = rpn_bbox_weight * sym.smooth_l1(
        bbox_pred - rpn_bbox_target, scalar=3.0, name="rpn_bbox_loss_")
    bbox_loss = sym.MakeLoss(bbox_loss_, name="rpn_bbox_loss",
                             grad_scale=1.0 / rpn_batch_size)
    return sym.Group([cls_prob, bbox_loss])
