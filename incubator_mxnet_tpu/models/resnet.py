"""ResNet v1/v2 (reference ``example/image-classification/symbols/resnet.py``)
— the flagship: BASELINE configs 2 and 5 (single-chip + v5p-32 dist_sync)
target ResNet-50 ImageNet ≥50% MFU.

TPU note: ``layout`` selects NCHW (reference default) or NHWC — the
channels-last layout the MXU natively tiles (reference ConvolutionParam also
exposed a layout option).  NHWC is what the benchmark uses (PERF.md).  bf16
training uses the ``dtype`` argument (cast at input + cast back before
softmax), matching how the reference used fp16
(``train_imagenet.py --dtype float16``).
"""
from .. import symbol as sym


def image_data_shape(image_shape, layout="NCHW"):
    """The data-variable shape (sans batch) for a CLI-style channels-first
    ``image_shape`` under the given layout — single source of the
    CHW→HWC convention used by ``resnet(layout="NHWC")`` and bench."""
    if layout == "NHWC":
        return (image_shape[1], image_shape[2], image_shape[0])
    if layout != "NCHW":
        raise ValueError("unsupported layout %r (NCHW or NHWC)" % (layout,))
    return tuple(image_shape)


def _bn_axis(layout):
    return 3 if layout == "NHWC" else 1


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True, bn_mom=0.9, workspace=256,
                  memonger=False, layout="NCHW", bn_extra=None):
    """A residual block (pre-activation, v2 — reference residual_unit).

    ``bn_extra``: extra attrs applied to every BatchNorm (e.g.
    ``{"ghost_sample": 4}`` for subsampled statistics, or
    ``{"use_global_stats": True}`` for the affine-only/frozen limit) —
    the HBM-roofline experiment knob, PERF.md §17."""
    ax = _bn_axis(layout)
    bn_extra = bn_extra or {}
    if bottle_neck:
        bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, axis=ax,
                            momentum=bn_mom, name=name + "_bn1",
                            **bn_extra)
        act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(act1, num_filter=int(num_filter * 0.25),
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, layout=layout,
                                name=name + "_conv1")
        bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5, axis=ax,
                            momentum=bn_mom, name=name + "_bn2",
                            **bn_extra)
        act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(act2, num_filter=int(num_filter * 0.25),
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, layout=layout,
                                name=name + "_conv2")
        bn3 = sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5, axis=ax,
                            momentum=bn_mom, name=name + "_bn3",
                            **bn_extra)
        act3 = sym.Activation(bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(act3, num_filter=num_filter, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True,
                                layout=layout, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(act1, num_filter=num_filter,
                                       kernel=(1, 1), stride=stride,
                                       no_bias=True, layout=layout,
                                       name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                        axis=ax, name=name + "_bn1", **bn_extra)
    act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(act1, num_filter=num_filter, kernel=(3, 3),
                            stride=stride, pad=(1, 1), no_bias=True,
                            layout=layout, name=name + "_conv1")
    bn2 = sym.BatchNorm(conv1, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                        axis=ax, name=name + "_bn2", **bn_extra)
    act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            layout=layout, name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(act1, num_filter=num_filter,
                                   kernel=(1, 1), stride=stride,
                                   no_bias=True, layout=layout,
                                   name=name + "_sc")
    return conv2 + shortcut


def _space_to_depth(data, image_shape, layout, block=2):
    """Re-lay (H, W, C) → (H/b, W/b, C·b²) so the stem conv reads a
    128-lane-friendly channel dim instead of C=3 (which tiles 3/128 lanes
    and makes the input BN/conv HBM-pathological — PERF.md §3).

    Both layouts merge channels in the SAME (bh, bw, c) order, preserving
    the repo's cross-layout contract: the identical OIHW weights load
    into the NCHW and NHWC nets directly (test_resnet_nhwc_matches_nchw).
    """
    if layout == "NHWC":
        h, w, c = image_shape
        r = sym.Reshape(data, shape=(0, h // block, block, w // block,
                                     block, c))
        t = sym.transpose(r, axes=(0, 1, 3, 2, 4, 5))
        return sym.Reshape(t, shape=(0, h // block, w // block,
                                     c * block * block))
    c, h, w = image_shape
    r = sym.Reshape(data, shape=(0, c, h // block, block, w // block,
                                 block))
    # (N, c, h2, bh, w2, bw) → (N, bh, bw, c, h2, w2): channel-minor c,
    # matching the NHWC merge order above
    t = sym.transpose(r, axes=(0, 3, 5, 1, 2, 4))
    return sym.Reshape(t, shape=(0, c * block * block, h // block,
                                 w // block))


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9, workspace=256, dtype="float32",
           memonger=False, layout="NCHW", stem="7x7", bn_extra=None):
    num_unit = len(units)
    assert num_unit == num_stages
    bn_extra = bn_extra or {}
    if stem not in ("7x7", "s2d"):
        raise ValueError("stem must be '7x7' or 's2d', got %r" % (stem,))
    ax = _bn_axis(layout)
    data = sym.Variable(name="data")
    if dtype == "float16" or dtype == "bfloat16":
        data = sym.Cast(data, dtype=dtype)
    height = image_shape[1] if layout == "NCHW" else image_shape[0]
    s2d = stem == "s2d" and height > 32
    if s2d:
        # space-to-depth stem (the standard TPU ResNet reformulation):
        # 224²×3 → 112²×12 re-lay, then a stride-1 3×3 conv — removes the
        # C=3 tiling pathology and the 112² stem-activation traffic.
        # Accuracy-equivalent variant, opt-in (weights are not
        # checkpoint-compatible with the 7×7 stem).
        data = _space_to_depth(data, image_shape, layout)
    data = sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=bn_mom,
                         axis=ax, name="bn_data", **bn_extra)
    if height <= 32:  # cifar-style stem
        body = sym.Convolution(data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, layout=layout, name="conv0")
    else:  # imagenet stem (7×7/2 reference form, or 3×3/1 on s2d input)
        body = sym.Convolution(
            data, num_filter=filter_list[0],
            kernel=(3, 3) if s2d else (7, 7),
            stride=(1, 1) if s2d else (2, 2),
            pad=(1, 1) if s2d else (3, 3),
            no_bias=True, layout=layout, name="conv0")
        body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, axis=ax,
                             momentum=bn_mom, name="bn0", **bn_extra)
        body = sym.Activation(body, act_type="relu", name="relu0")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", layout=layout)

    for i in range(num_stages):
        body = residual_unit(
            body, filter_list[i + 1],
            (1 if i == 0 else 2, 1 if i == 0 else 2),
            False, name="stage%d_unit%d" % (i + 1, 1),
            bottle_neck=bottle_neck, bn_mom=bn_mom, workspace=workspace,
            memonger=memonger, layout=layout, bn_extra=bn_extra)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck=bottle_neck, bn_mom=bn_mom,
                                 workspace=workspace, memonger=memonger,
                                 layout=layout, bn_extra=bn_extra)
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        axis=ax, name="bn1", **bn_extra)
    relu1 = sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(relu1, global_pool=True, kernel=(7, 7),
                        pool_type="avg", layout=layout, name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    if dtype in ("float16", "bfloat16"):
        fc1 = sym.Cast(fc1, dtype="float32")
    return sym.SoftmaxOutput(fc1, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               conv_workspace=256, dtype="float32", layout="NCHW", **kwargs):
    """Depth → units table exactly as the reference resnet.py.

    ``image_shape`` is always given channels-first (C, H, W) as in the
    reference CLI; with ``layout="NHWC"`` the data variable is expected
    as (N, H, W, C)."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    height = image_shape[1]
    if height <= 32:            # such as cifar10 (reference resnet.py:117)
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            bottle_neck = False
        else:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = per_unit * num_stages
    else:
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            bottle_neck = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            bottle_neck = False
        num_stages = 4
        units_map = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3],
                     50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                     152: [3, 8, 36, 3], 200: [3, 24, 36, 3],
                     269: [3, 30, 48, 8]}
        if num_layers not in units_map:
            raise ValueError("no experiments done on num_layers %d"
                             % num_layers)
        units = units_map[num_layers]

    shape_for_stem = image_data_shape(image_shape, layout)
    return resnet(units=units, num_stages=num_stages,
                  filter_list=filter_list, num_classes=num_classes,
                  image_shape=shape_for_stem, bottle_neck=bottle_neck,
                  workspace=conv_workspace, dtype=dtype, layout=layout,
                  **kwargs)
