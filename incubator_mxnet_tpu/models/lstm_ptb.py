"""PTB word-level language model — BASELINE config 3.

Reference analog: ``example/rnn/lstm_bucketing.py`` (stacked LSTM over
embeddings, per-bucket unroll, SoftmaxOutput over the flattened time
dim).  TPU-native: the same sym_gen works with either unrolled cells
(static graph per bucket) or ``FusedRNNCell`` (one ``lax.scan`` per
layer, preferred on TPU — no per-bucket recompile of the recurrence).
"""
from __future__ import annotations

from .. import rnn as rnn_mod
from .. import symbol as sym

__all__ = ["lstm_ptb_sym_gen", "get_symbol"]


def lstm_ptb_sym_gen(num_embed=200, num_hidden=200, num_layers=2,
                     vocab_size=10000, dropout=0.0, fused=True):
    """Returns ``sym_gen(seq_len) -> (symbol, data_names, label_names)``
    for BucketingModule."""

    if fused:
        stack = rnn_mod.FusedRNNCell(num_hidden, num_layers=num_layers,
                                     mode="lstm", dropout=dropout,
                                     prefix="lstm_")
    else:
        stack = rnn_mod.SequentialRNNCell()
        for i in range(num_layers):
            stack.add(rnn_mod.LSTMCell(num_hidden,
                                       prefix="lstm_l%d_" % i))
            if dropout > 0 and i < num_layers - 1:
                stack.add(rnn_mod.DropoutCell(dropout))

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.Embedding(data, input_dim=vocab_size,
                              output_dim=num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size,
                                  name="pred")
        label_flat = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, label_flat, name="softmax")
        return out, ("data",), ("softmax_label",)

    return sym_gen


def get_symbol(seq_len=35, **kwargs):
    return lstm_ptb_sym_gen(**kwargs)(seq_len)[0]
