"""SSD-VGG16 detector (BASELINE config 4).

Reference analogs: ``example/ssd/symbol/vgg16_reduced.py`` (base network),
``example/ssd/symbol/common.py:96-300`` (multi-layer features + multibox
heads), ``example/ssd/symbol/symbol_builder.py:29-160`` (train/deploy
symbols), ``example/ssd/symbol/symbol_factory.py:22-60`` (ssd300 config).

The training head wires ``_contrib_MultiBoxTarget`` → SoftmaxOutput (class
loss with ignore) + smooth-L1 MakeLoss (location loss); the deploy head
ends in ``_contrib_MultiBoxDetection``.
"""
from __future__ import annotations

import numpy as np

from .. import symbol as sym
from ..contrib import symbol as contrib_sym

__all__ = ["vgg16_reduced", "get_symbol_train", "get_symbol", "ssd_300"]


def vgg16_reduced():
    """VGG16 with fc6/fc7 as (dilated) convolutions, SSD flavor
    (vgg16_reduced.py:20-95).  Returns the relu7 feature symbol."""
    data = sym.Variable("data")
    body = data
    # (num convs, channels) per stage; pool3 uses ceil-mode in caffe SSD —
    # XLA pooling is floor-mode, identical for the 300x300 config's shapes
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for i, (n, f) in enumerate(cfg):
        for j in range(n):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=f,
                                   name="conv%d_%d" % (i + 1, j + 1))
            body = sym.Activation(body, act_type="relu",
                                  name="relu%d_%d" % (i + 1, j + 1))
        if i < 4:
            body = sym.Pooling(body, pool_type="max", kernel=(2, 2),
                               stride=(2, 2), name="pool%d" % (i + 1))
    body = sym.Pooling(body, pool_type="max", kernel=(3, 3), stride=(1, 1),
                       pad=(1, 1), name="pool5")
    body = sym.Convolution(body, kernel=(3, 3), pad=(6, 6), dilate=(6, 6),
                           num_filter=1024, name="fc6")
    body = sym.Activation(body, act_type="relu", name="relu6")
    body = sym.Convolution(body, kernel=(1, 1), num_filter=1024, name="fc7")
    body = sym.Activation(body, act_type="relu", name="relu7")
    return body


# ssd300 config (symbol_factory.py:36-46)
_SSD300 = dict(
    from_layers=["relu4_3", "relu7", "", "", "", ""],
    num_filters=[512, -1, 512, 256, 256, 256],
    strides=[-1, -1, 2, 2, 1, 1],
    pads=[-1, -1, 1, 1, 0, 0],
    sizes=[[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
           [0.71, 0.79], [0.88, 0.961]],
    ratios=[[1, 2, 0.5], [1, 2, 0.5, 3, 1.0 / 3], [1, 2, 0.5, 3, 1.0 / 3],
            [1, 2, 0.5, 3, 1.0 / 3], [1, 2, 0.5], [1, 2, 0.5]],
    normalizations=[20, -1, -1, -1, -1, -1],
    steps=[x / 300.0 for x in (8, 16, 32, 64, 100, 300)],
)


def _conv_act(layer, name, num_filter, kernel, pad, stride):
    c = sym.Convolution(layer, kernel=kernel, pad=pad, stride=stride,
                        num_filter=num_filter, name="%s_conv" % name)
    return sym.Activation(c, act_type="relu", name="%s_relu" % name)


def multi_layer_feature(body, from_layers, num_filters, strides, pads,
                        min_filter=128):
    """Pick feature maps out of the base net and grow extra 1x1→3x3 stride-2
    pyramids on top (common.py:96-152)."""
    internals = body.get_internals()
    layers = []
    for k, (from_layer, num_filter, s, p) in enumerate(
            zip(from_layers, num_filters, strides, pads)):
        if from_layer.strip():
            layers.append(internals[from_layer.strip() + "_output"])
        else:
            layer = layers[-1]
            num_1x1 = max(min_filter, num_filter // 2)
            c1 = _conv_act(layer, "multi_feat_%d_conv_1x1" % k, num_1x1,
                           (1, 1), (0, 0), (1, 1))
            c3 = _conv_act(c1, "multi_feat_%d_conv_3x3" % k, num_filter,
                           (3, 3), (p, p), (s, s))
            layers.append(c3)
    return layers


def multibox_layer(from_layers, num_classes, sizes, ratios, normalization,
                   num_channels, clip=False, steps=()):
    """Per-scale loc/cls conv heads + anchors, concatenated
    (common.py:153-300).  ``num_classes`` here EXCLUDES background; one
    background class is prepended, label 0."""
    assert num_classes > 0
    n = len(from_layers)
    if not isinstance(ratios[0], (list, tuple)):
        ratios = [ratios] * n
    if not isinstance(normalization, (list, tuple)):
        normalization = [normalization] * n
    num_channels = list(num_channels)
    num_classes += 1  # background = class 0
    loc_layers, cls_layers, anchor_layers = [], [], []
    for k, from_layer in enumerate(from_layers):
        from_name = from_layer.name
        if normalization[k] > 0:
            from_layer = sym.L2Normalization(from_layer, mode="channel",
                                             name="%s_norm" % from_name)
            scale = sym.Variable(
                "%s_scale" % from_name,
                shape=(1, num_channels.pop(0), 1, 1),
                init="[\"constant\", {\"value\": %f}]" % normalization[k],
                wd_mult=0.1)
            from_layer = sym.broadcast_mul(scale, from_layer)
        size, ratio = sizes[k], ratios[k]
        num_anchors = len(size) - 1 + len(ratio)

        loc_pred = sym.Convolution(
            from_layer, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
            num_filter=num_anchors * 4,
            name="%s_loc_pred_conv" % from_name)
        loc_pred = sym.transpose(loc_pred, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Flatten(loc_pred))

        cls_pred = sym.Convolution(
            from_layer, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
            num_filter=num_anchors * num_classes,
            name="%s_cls_pred_conv" % from_name)
        cls_pred = sym.transpose(cls_pred, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Flatten(cls_pred))

        step = (steps[k], steps[k]) if steps else (-1.0, -1.0)
        anchors = contrib_sym.MultiBoxPrior(
            from_layer, sizes=str(tuple(size)), ratios=str(tuple(ratio)),
            clip=clip, steps=str(step), name="%s_anchors" % from_name)
        anchor_layers.append(sym.Flatten(anchors))

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(cls_preds, shape=(0, -1, num_classes))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchor_boxes = sym.Concat(*anchor_layers, dim=1)
    anchor_boxes = sym.Reshape(anchor_boxes, shape=(0, -1, 4),
                               name="multibox_anchors")
    return loc_preds, cls_preds, anchor_boxes


def get_symbol_train(num_classes=20, nms_thresh=0.5, force_suppress=False,
                     nms_topk=400, **config):
    """SSD training symbol: Group([cls_prob, loc_loss, cls_label, det])
    (symbol_builder.py:29-117)."""
    cfg = dict(_SSD300)
    cfg.update(config)
    label = sym.Variable("label")
    body = vgg16_reduced()
    layers = multi_layer_feature(body, cfg["from_layers"],
                                 cfg["num_filters"], cfg["strides"],
                                 cfg["pads"])
    loc_preds, cls_preds, anchor_boxes = multibox_layer(
        layers, num_classes, cfg["sizes"], cfg["ratios"],
        cfg["normalizations"], cfg["num_filters"], clip=False,
        steps=cfg["steps"])

    tmp = contrib_sym.MultiBoxTarget(
        anchor_boxes, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1, negative_mining_ratio=3,
        minimum_negative_samples=0, negative_mining_thresh=0.5,
        variances="(0.1, 0.1, 0.2, 0.2)", name="multibox_target")
    loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]

    cls_prob = sym.SoftmaxOutput(cls_preds, cls_target, ignore_label=-1,
                                 use_ignore=True, grad_scale=1.0,
                                 multi_output=True, normalization="valid",
                                 name="cls_prob")
    loc_loss_ = sym.smooth_l1(loc_target_mask * (loc_preds - loc_target),
                              scalar=1.0, name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.0,
                            normalization="valid", name="loc_loss")
    cls_label = sym.MakeLoss(cls_target, grad_scale=0, name="cls_label")
    det = contrib_sym.MultiBoxDetection(
        cls_prob, loc_preds, anchor_boxes, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances="(0.1, 0.1, 0.2, 0.2)", nms_topk=nms_topk)
    det = sym.MakeLoss(det, grad_scale=0, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, **config):
    """SSD inference symbol ending in MultiBoxDetection
    (symbol_builder.py:118-160)."""
    cfg = dict(_SSD300)
    cfg.update(config)
    body = vgg16_reduced()
    layers = multi_layer_feature(body, cfg["from_layers"],
                                 cfg["num_filters"], cfg["strides"],
                                 cfg["pads"])
    loc_preds, cls_preds, anchor_boxes = multibox_layer(
        layers, num_classes, cfg["sizes"], cfg["ratios"],
        cfg["normalizations"], cfg["num_filters"], clip=False,
        steps=cfg["steps"])
    cls_prob = sym.softmax(cls_preds, axis=1, name="cls_prob")
    return contrib_sym.MultiBoxDetection(
        cls_prob, loc_preds, anchor_boxes, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances="(0.1, 0.1, 0.2, 0.2)", nms_topk=nms_topk)


def ssd_300(num_classes=20, train=True, **kwargs):
    """Convenience entry matching ``symbol_factory.get_symbol*('vgg16_reduced',
    300, ...)``."""
    fn = get_symbol_train if train else get_symbol
    return fn(num_classes=num_classes, **kwargs)
