"""DCGAN generator/discriminator (reference ``example/gan/dcgan.py``
``make_dcgan_sym``): the adversarial-training example family, and the
exerciser of ``Deconvolution`` + external-gradient ``Module.backward``.

``size`` scales the image (64 = the reference's 64×64; 32 drops one
up/down block for fast smoke runs).
"""
from __future__ import annotations

from .. import symbol as sym


def make_dcgan_sym(ngf=64, ndf=64, nc=3, size=64, no_bias=True,
                   fix_gamma=True, eps=1e-5 + 1e-12):
    """-> (generator_sym, discriminator_sym).

    Generator: rand (B, Z, 1, 1) → tanh image (B, nc, size, size).
    Discriminator: image → logistic real/fake loss vs ``label``.
    """
    assert size in (32, 64), "size must be 32 or 64"
    n_up = 3 if size == 32 else 4
    BatchNorm = sym.BatchNorm

    rand = sym.Variable("rand")
    g = sym.Deconvolution(rand, name="g1", kernel=(4, 4),
                          num_filter=ngf * 2 ** n_up // 2,
                          no_bias=no_bias)
    g = BatchNorm(g, name="gbn1", fix_gamma=fix_gamma, eps=eps)
    g = sym.Activation(g, name="gact1", act_type="relu")
    for i in range(n_up - 1):
        filt = ngf * 2 ** (n_up - 2 - i)
        g = sym.Deconvolution(g, name="g%d" % (i + 2), kernel=(4, 4),
                              stride=(2, 2), pad=(1, 1),
                              num_filter=filt, no_bias=no_bias)
        g = BatchNorm(g, name="gbn%d" % (i + 2), fix_gamma=fix_gamma,
                      eps=eps)
        g = sym.Activation(g, name="gact%d" % (i + 2), act_type="relu")
    g = sym.Deconvolution(g, name="g%d" % (n_up + 1), kernel=(4, 4),
                          stride=(2, 2), pad=(1, 1), num_filter=nc,
                          no_bias=no_bias)
    gout = sym.Activation(g, name="gact_out", act_type="tanh")

    data = sym.Variable("data")
    label = sym.Variable("label")
    d = sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf, no_bias=no_bias)
    d = sym.LeakyReLU(d, name="dact1", act_type="leaky", slope=0.2)
    for i in range(n_up - 1):
        d = sym.Convolution(d, name="d%d" % (i + 2), kernel=(4, 4),
                            stride=(2, 2), pad=(1, 1),
                            num_filter=ndf * 2 ** (i + 1),
                            no_bias=no_bias)
        d = BatchNorm(d, name="dbn%d" % (i + 2), fix_gamma=fix_gamma,
                      eps=eps)
        d = sym.LeakyReLU(d, name="dact%d" % (i + 2), act_type="leaky",
                          slope=0.2)
    d = sym.Convolution(d, name="d%d" % (n_up + 1), kernel=(4, 4),
                        num_filter=1, no_bias=no_bias)
    d = sym.Flatten(d)
    dloss = sym.LogisticRegressionOutput(d, label, name="dloss")
    return gout, dloss
