"""Gluon neural-network layers (``python/mxnet/gluon/nn/``)."""
from .basic_layers import (Sequential, HybridSequential, Dense, Activation,
                           Dropout, BatchNorm, LeakyReLU, Embedding,
                           Flatten, Lambda, HybridLambda)
from .conv_layers import (Conv1D, Conv2D, Conv3D, Conv2DTranspose,
                          MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D,
                          AvgPool2D, AvgPool3D, GlobalMaxPool2D,
                          GlobalAvgPool2D, GlobalAvgPool1D, GlobalMaxPool1D)

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Conv1D", "Conv2D", "Conv3D",
           "Conv2DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool2D",
           "GlobalAvgPool2D", "GlobalAvgPool1D", "GlobalMaxPool1D"]
