"""Basic gluon layers (``python/mxnet/gluon/nn/basic_layers.py``)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class Dense(HybridBlock):
    """Fully connected layer (reference ``nn.Dense``)."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, weight_initializer=None,
                 bias_initializer="zero", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate)


class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, beta_initializer="zero",
                 gamma_initializer="one", running_mean_initializer="zero",
                 running_variance_initializer="one", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale}
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer,
                allow_deferred_init=True, differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_spec = function

    def hybrid_forward(self, F, *args):
        fn = getattr(F, self._func_spec) \
            if isinstance(self._func_spec, str) else self._func_spec
        if isinstance(self._func_spec, str):
            return fn(*args)
        return self._func_spec(F, *args)
