"""Gluon convolution / pooling layers
(``python/mxnet/gluon/nn/conv_layers.py``)."""
from __future__ import annotations

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "MaxPool1D",
           "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool2D", "GlobalAvgPool2D", "GlobalAvgPool1D",
           "GlobalMaxPool1D"]


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, in_channels, activation, use_bias,
                 weight_initializer, bias_initializer, op_name, ndim,
                 op_extra=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        kernel_size = _pair(kernel_size, ndim)
        strides = _pair(strides, ndim)
        padding = _pair(padding, ndim)
        dilation = _pair(dilation, ndim)
        self._op_name = op_name
        self._kwargs = {"kernel": kernel_size, "stride": strides,
                        "pad": padding, "dilate": dilation,
                        "num_filter": channels, "num_group": groups,
                        "no_bias": not use_bias}
        if op_extra:
            self._kwargs.update(op_extra)
        if op_name == "Deconvolution":
            wshape = (in_channels, channels // groups) + kernel_size
        else:
            wshape = (channels, in_channels // max(groups, 1)) \
                + kernel_size if in_channels else \
                (channels, 0) + kernel_size
        with self.name_scope():
            self.weight = self.params.get("weight", shape=wshape,
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation else None

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            kw = dict(self._kwargs, no_bias=False)
            out = op(x, weight, bias, **kw)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zero", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         "Convolution", 1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zero", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         "Convolution", 2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zero",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         "Convolution", 3, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zero",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         "Deconvolution", 2,
                         op_extra={"adj": _pair(output_padding, 2)},
                         **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ndim, global_pool,
                 pool_type, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {"kernel": _pair(pool_size, ndim),
                        "stride": _pair(strides, ndim),
                        "pad": _pair(padding, ndim),
                        "global_pool": global_pool,
                        "pool_type": pool_type}

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, 1, False, "max",
                         **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, 2, False, "max",
                         **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 **kwargs):
        super().__init__(pool_size, strides, padding, 3, False, "max",
                         **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, 1, False, "avg",
                         **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, 2, False, "avg",
                         **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 **kwargs):
        super().__init__(pool_size, strides, padding, 3, False, "avg",
                         **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, **kwargs):
        super().__init__((1,), None, 0, 1, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, **kwargs):
        super().__init__((1,), None, 0, 1, True, "avg", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, **kwargs):
        super().__init__((1, 1), None, 0, 2, True, "max", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, **kwargs):
        super().__init__((1, 1), None, 0, 2, True, "avg", **kwargs)
