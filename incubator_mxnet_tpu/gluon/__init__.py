"""Gluon — imperative/hybrid NN API (``python/mxnet/gluon/``)."""
from .parameter import Parameter, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import data
from . import utils
from . import model_zoo
from . import rnn

__all__ = ["Parameter", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "loss", "data", "utils",
           "model_zoo", "rnn"]
