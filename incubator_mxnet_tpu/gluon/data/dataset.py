"""Datasets (``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(x, *rest):
            return (fn(x),) + rest if rest else fn(x)

        return self.transform(
            lambda *item: (fn(item[0]),) + item[1:]
            if len(item) > 1 else fn(item[0]))


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of arrays/lists (reference ``ArrayDataset``)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (``gluon/data/dataset.py``
    RecordFileDataset, backed by our recordio module)."""

    def __init__(self, filename):
        from ...recordio import IndexedRecordIO

        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = IndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
