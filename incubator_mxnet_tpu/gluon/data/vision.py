"""Vision datasets (``python/mxnet/gluon/data/vision.py``): MNIST,
FashionMNIST, CIFAR10 — reading the standard on-disk formats when present,
else deterministic synthetic data (zero-egress environment, SURVEY.md §4
"synthetic data" fixture philosophy)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...ndarray import array as nd_array
from .dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    _N_SYNTH = 6000

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        prefix = "train" if self._train else "t10k"
        img_path = os.path.join(self._root,
                                "%s-images-idx3-ubyte.gz" % prefix)
        lbl_path = os.path.join(self._root,
                                "%s-labels-idx1-ubyte.gz" % prefix)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = np.frombuffer(f.read(), dtype=np.uint8
                                      ).astype(np.int32)
            with gzip.open(img_path, "rb") as f:
                struct.unpack(">IIII", f.read(16))
                data = np.frombuffer(f.read(), dtype=np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
        else:
            rng = np.random.RandomState(42 if self._train else 43)
            n = self._N_SYNTH if self._train else self._N_SYNTH // 6
            templates = rng.rand(10, 28, 28, 1)
            label = rng.randint(0, 10, n).astype(np.int32)
            data = np.clip(templates[label]
                           + rng.randn(n, 28, 28, 1) * 0.3, 0, 1) * 255
            data = data.astype(np.uint8)
        self._data = nd_array(data, dtype=np.uint8)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super(MNIST, self).__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        fname = os.path.join(self._root,
                             "data_batch_1.bin" if self._train
                             else "test_batch.bin")
        if os.path.exists(fname):
            data, label = [], []
            files = ["data_batch_%d.bin" % i for i in range(1, 6)] \
                if self._train else ["test_batch.bin"]
            for f in files:
                raw = np.fromfile(os.path.join(self._root, f),
                                  dtype=np.uint8)
                raw = raw.reshape(-1, 3073)
                label.append(raw[:, 0].astype(np.int32))
                data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                            .transpose(0, 2, 3, 1))
            data = np.concatenate(data)
            label = np.concatenate(label)
        else:
            rng = np.random.RandomState(7 if self._train else 8)
            n = 5000 if self._train else 1000
            templates = rng.rand(10, 32, 32, 3)
            label = rng.randint(0, 10, n).astype(np.int32)
            data = np.clip(templates[label]
                           + rng.randn(n, 32, 32, 3) * 0.25, 0, 1) * 255
            data = data.astype(np.uint8)
        self._data = nd_array(data, dtype=np.uint8)
        self._label = label


class ImageRecordDataset(RecordFileDataset):
    """Dataset over a RecordIO file of packed images (reference
    ``gluon/data/vision.py:166``): each item decodes to
    (image NDArray HWC, label)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ... import image as image_mod
        from ...recordio import unpack

        record = super().__getitem__(idx)
        header, img = unpack(record)
        data = image_mod.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class ImageFolderDataset(Dataset):
    """Dataset over a class-per-subdirectory image tree (reference
    ``gluon/data/vision.py:197``); ``synsets[i]`` names label ``i``."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png")
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        from ... import image as image_mod

        fname, label = self.items[idx]
        data = image_mod.imread(fname, self._flag)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self):
        return len(self.items)
