"""DataLoader (``python/mxnet/gluon/data/dataloader.py:40-84`` — the
reference at v0.11 is single-threaded; we match that API and add optional
thread-based prefetch, the TPU-host analog of its later worker pools)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...ndarray import array as nd_array
from ...ndarray.ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack samples into a batch."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no "
                                 "batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise MXNetError("batch_size/shuffle/sampler/last_batch "
                             "conflict with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        if num_workers < 0:
            raise MXNetError("num_workers must be >= 0")
        self._num_workers = num_workers

    def _fetch(self, batch):
        return self._batchify_fn([self._dataset[int(i)] for i in batch])

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._fetch(batch)
            return
        # one batch per worker task, up to 2*num_workers batches in flight,
        # yielded in sampler order (thread-based: TPU hosts feed the device
        # from host RAM, so decode/augment in __getitem__ releases the GIL
        # in numpy/PIL and threads suffice — the role of the reference's
        # later multiprocessing workers)
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(self._num_workers)
        try:
            pending = deque()
            for batch in self._batch_sampler:
                pending.append(pool.submit(self._fetch, batch))
                if len(pending) >= 2 * self._num_workers:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()
        finally:
            # early abandonment (break / next(iter(...))) must not block
            # on ~2N queued prefetches: drop what never started, don't
            # wait for what did
            pool.shutdown(wait=False, cancel_futures=True)

    def __len__(self):
        return len(self._batch_sampler)
