"""Zoo-internal container layers
(``python/mxnet/gluon/model_zoo/custom_layers.py``)."""
from __future__ import annotations

from ..nn.basic_layers import HybridSequential
from ..block import HybridBlock

__all__ = ["HybridConcurrent", "Identity"]


class HybridConcurrent(HybridSequential):
    """Run each child on the same input and concatenate the outputs along
    ``concat_dim`` (reference ``custom_layers.py:HybridConcurrent``)."""

    def __init__(self, concat_dim=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.concat_dim = concat_dim

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children]
        return F.concat(*out, dim=self.concat_dim)


class Identity(HybridBlock):
    """Pass-through block (reference ``custom_layers.py:Identity``)."""

    def hybrid_forward(self, F, x):
        return x
