"""Gluon vision model zoo
(``python/mxnet/gluon/model_zoo/vision/``: alexnet, densenet, inception,
resnet v1/v2, squeezenet, vgg).  Pretrained-weight download is not available
in this zero-egress environment; ``pretrained=True`` loads from a local
``root`` path when the file exists and raises otherwise (the
``model_store.py`` role)."""
from __future__ import annotations

import os

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock
from .custom_layers import HybridConcurrent

__all__ = ["get_model", "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "vgg11", "vgg13",
           "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
           "alexnet", "squeezenet1_0", "squeezenet1_1", "densenet121",
           "densenet161", "densenet169", "densenet201", "inception_v3",
           "mlp_model"]


def _maybe_load(net, name, pretrained, root, ctx):
    if pretrained:
        path = os.path.join(os.path.expanduser(root), "%s.params" % name)
        if not os.path.exists(path):
            raise MXNetError(
                "pretrained weights for %s not found at %s (no network "
                "egress; place weights there manually)" % (name, path))
        net.load_params(path, ctx=ctx)
    return net


# ---------------------------------------------------------------------------
# ResNet v1/v2
# ---------------------------------------------------------------------------


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels, 3, stride, 1,
                                in_channels=in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, in_channels=channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, 1, stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          use_bias=False))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


_RESNET_SPEC = {18: ("basic", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
                34: ("basic", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
                50: ("bottle", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
                101: ("bottle", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
                152: ("bottle", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes)

    def _make_layer(self, block, layers, channels, stride, stage_index):
        layer = nn.HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, True))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(ResNetV1):
    def __init__(self, block, layers, channels, classes=1000, **kwargs):
        HybridBlock.__init__(self, **kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes)


def _resnet(version, num_layers, pretrained=False, ctx=None,
            root="~/.mxnet/models", **kwargs):
    kind, layers, channels = _RESNET_SPEC[num_layers]
    if version == 1:
        block = BasicBlockV1 if kind == "basic" else BottleneckV1
        net = ResNetV1(block, layers, channels, **kwargs)
    else:
        block = BasicBlockV2 if kind == "basic" else BottleneckV2
        net = ResNetV2(block, layers, channels, **kwargs)
    return _maybe_load(net, "resnet%d_v%d" % (num_layers, version),
                       pretrained, root, ctx)


def resnet18_v1(**kw):
    return _resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return _resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return _resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return _resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return _resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return _resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return _resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return _resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return _resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return _resnet(2, 152, **kw)


# ---------------------------------------------------------------------------
# VGG
# ---------------------------------------------------------------------------

_VGG_SPEC = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], 3, 1, 1))
                    if batch_norm:
                        self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _vgg(num_layers, batch_norm=False, pretrained=False, ctx=None,
         root="~/.mxnet/models", **kwargs):
    layers, filters = _VGG_SPEC[num_layers]
    net = VGG(layers, filters, batch_norm=batch_norm, **kwargs)
    name = "vgg%d%s" % (num_layers, "_bn" if batch_norm else "")
    return _maybe_load(net, name, pretrained, root, ctx)


def vgg11(**kw):
    return _vgg(11, **kw)


def vgg13(**kw):
    return _vgg(13, **kw)


def vgg16(**kw):
    return _vgg(16, **kw)


def vgg19(**kw):
    return _vgg(19, **kw)


def vgg11_bn(**kw):
    return _vgg(11, batch_norm=True, **kw)


def vgg13_bn(**kw):
    return _vgg(13, batch_norm=True, **kw)


def vgg16_bn(**kw):
    return _vgg(16, batch_norm=True, **kw)


def vgg19_bn(**kw):
    return _vgg(19, batch_norm=True, **kw)


# ---------------------------------------------------------------------------
# AlexNet / SqueezeNet / DenseNet
# ---------------------------------------------------------------------------


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 11, 4, 2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(192, 5, padding=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(384, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root="~/.mxnet/models", **kwargs):
    return _maybe_load(AlexNet(**kwargs), "alexnet", pretrained, root, ctx)


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1, 1, activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3, 3, padding=1,
                                   activation="relu")

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.Concat(self.expand1x1(x), self.expand3x3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(64, 256, 256))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(3, 2))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(64, 256, 256))
                self.features.add(_Fire(64, 256, 256))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, ctx=None, root="~/.mxnet/models",
                  **kwargs):
    return _maybe_load(SqueezeNet("1.0", **kwargs), "squeezenet1.0",
                       pretrained, root, ctx)


def squeezenet1_1(pretrained=False, ctx=None, root="~/.mxnet/models",
                  **kwargs):
    return _maybe_load(SqueezeNet("1.1", **kwargs), "squeezenet1.1",
                       pretrained, root, ctx)


class _DenseBlock(HybridBlock):
    def __init__(self, num_layers, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self._layers = []
        for _ in range(num_layers):
            layer = nn.HybridSequential(prefix="")
            layer.add(nn.BatchNorm())
            layer.add(nn.Activation("relu"))
            layer.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False))
            layer.add(nn.BatchNorm())
            layer.add(nn.Activation("relu"))
            layer.add(nn.Conv2D(growth_rate, 3, padding=1,
                                use_bias=False))
            if dropout:
                layer.add(nn.Dropout(dropout))
            self.register_child(layer)
            self._layers.append(layer)

    def hybrid_forward(self, F, x):
        for layer in self._layers:
            out = layer(x)
            x = F.Concat(x, out, dim=1)
        return x


def _transition(num_output):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output, 1, use_bias=False))
    out.add(nn.AvgPool2D(2, 2))
    return out


_DENSENET_SPEC = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_DenseBlock(num_layers, growth_rate,
                                              bn_size, dropout))
                num_features += num_layers * growth_rate
                if i != len(block_config) - 1:
                    self.features.add(_transition(num_features // 2))
                    num_features //= 2
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _densenet(num_layers, pretrained=False, ctx=None,
              root="~/.mxnet/models", **kwargs):
    init_f, growth, cfg = _DENSENET_SPEC[num_layers]
    net = DenseNet(init_f, growth, cfg, **kwargs)
    return _maybe_load(net, "densenet%d" % num_layers, pretrained, root,
                       ctx)


def densenet121(**kw):
    return _densenet(121, **kw)


def densenet161(**kw):
    return _densenet(161, **kw)


def densenet169(**kw):
    return _densenet(169, **kw)


def densenet201(**kw):
    return _densenet(201, **kw)


# ---------------------------------------------------------------------------
# Inception v3 (``python/mxnet/gluon/model_zoo/vision/inception.py``)
# ---------------------------------------------------------------------------


def _inc_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _inc_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    names = ["channels", "kernel_size", "strides", "padding"]
    for setting in conv_settings:
        kw = {names[i]: v for i, v in enumerate(setting) if v is not None}
        out.add(_inc_conv(**kw))
    return out


def _inc_A(pool_features, prefix):
    out = HybridConcurrent(concat_dim=1, prefix=prefix)
    with out.name_scope():
        out.add(_inc_branch(None, (64, 1, None, None)))
        out.add(_inc_branch(None, (48, 1, None, None), (64, 5, None, 2)))
        out.add(_inc_branch(None, (64, 1, None, None), (96, 3, None, 1),
                            (96, 3, None, 1)))
        out.add(_inc_branch("avg", (pool_features, 1, None, None)))
    return out


def _inc_B(prefix):
    out = HybridConcurrent(concat_dim=1, prefix=prefix)
    with out.name_scope():
        out.add(_inc_branch(None, (384, 3, 2, None)))
        out.add(_inc_branch(None, (64, 1, None, None), (96, 3, None, 1),
                            (96, 3, 2, None)))
        out.add(_inc_branch("max"))
    return out


def _inc_C(channels_7x7, prefix):
    out = HybridConcurrent(concat_dim=1, prefix=prefix)
    with out.name_scope():
        out.add(_inc_branch(None, (192, 1, None, None)))
        out.add(_inc_branch(None, (channels_7x7, 1, None, None),
                            (channels_7x7, (1, 7), None, (0, 3)),
                            (192, (7, 1), None, (3, 0))))
        out.add(_inc_branch(None, (channels_7x7, 1, None, None),
                            (channels_7x7, (7, 1), None, (3, 0)),
                            (channels_7x7, (1, 7), None, (0, 3)),
                            (channels_7x7, (7, 1), None, (3, 0)),
                            (192, (1, 7), None, (0, 3))))
        out.add(_inc_branch("avg", (192, 1, None, None)))
    return out


def _inc_D(prefix):
    out = HybridConcurrent(concat_dim=1, prefix=prefix)
    with out.name_scope():
        out.add(_inc_branch(None, (192, 1, None, None),
                            (320, 3, 2, None)))
        out.add(_inc_branch(None, (192, 1, None, None),
                            (192, (1, 7), None, (0, 3)),
                            (192, (7, 1), None, (3, 0)),
                            (192, 3, 2, None)))
        out.add(_inc_branch("max"))
    return out


def _inc_E(prefix):
    out = HybridConcurrent(concat_dim=1, prefix=prefix)
    with out.name_scope():
        out.add(_inc_branch(None, (320, 1, None, None)))

        branch_3x3 = nn.HybridSequential(prefix="")
        out.add(branch_3x3)
        branch_3x3.add(_inc_branch(None, (384, 1, None, None)))
        split_3x3 = HybridConcurrent(concat_dim=1, prefix="")
        split_3x3.add(_inc_branch(None, (384, (1, 3), None, (0, 1))))
        split_3x3.add(_inc_branch(None, (384, (3, 1), None, (1, 0))))
        branch_3x3.add(split_3x3)

        branch_dbl = nn.HybridSequential(prefix="")
        out.add(branch_dbl)
        branch_dbl.add(_inc_branch(None, (448, 1, None, None),
                                   (384, 3, None, 1)))
        split_dbl = HybridConcurrent(concat_dim=1, prefix="")
        branch_dbl.add(split_dbl)
        split_dbl.add(_inc_branch(None, (384, (1, 3), None, (0, 1))))
        split_dbl.add(_inc_branch(None, (384, (3, 1), None, (1, 0))))

        out.add(_inc_branch("avg", (192, 1, None, None)))
    return out


class Inception3(HybridBlock):
    """Inception v3 (reference ``inception.py:Inception3``; input 299²)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_inc_conv(channels=32, kernel_size=3,
                                        strides=2))
            self.features.add(_inc_conv(channels=32, kernel_size=3))
            self.features.add(_inc_conv(channels=64, kernel_size=3,
                                        padding=1))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_inc_conv(channels=80, kernel_size=1))
            self.features.add(_inc_conv(channels=192, kernel_size=3))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_inc_A(32, "A1_"))
            self.features.add(_inc_A(64, "A2_"))
            self.features.add(_inc_A(64, "A3_"))
            self.features.add(_inc_B("B_"))
            self.features.add(_inc_C(128, "C1_"))
            self.features.add(_inc_C(160, "C2_"))
            self.features.add(_inc_C(160, "C3_"))
            self.features.add(_inc_C(192, "C4_"))

            self.classifier = nn.HybridSequential(prefix="")
            self.classifier.add(_inc_D("D_"))
            self.classifier.add(_inc_E("E1_"))
            self.classifier.add(_inc_E("E2_"))
            self.classifier.add(nn.AvgPool2D(pool_size=8))
            self.classifier.add(nn.Dropout(0.5))
            self.classifier.add(nn.Flatten())
            self.classifier.add(nn.Dense(classes))

    def hybrid_forward(self, F, x):
        return self.classifier(self.features(x))


def inception_v3(pretrained=False, ctx=None, root="~/.mxnet/models",
                 **kwargs):
    net = Inception3(**kwargs)
    return _maybe_load(net, "inceptionv3", pretrained, root, ctx)


def mlp_model(classes=10, **kwargs):
    net = nn.HybridSequential(**kwargs)
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"), nn.Dense(classes))
    return net


_MODELS = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _MODELS:
        raise MXNetError("model %s not in zoo; available: %s"
                         % (name, sorted(_MODELS)))
    return _MODELS[name](**kwargs)
