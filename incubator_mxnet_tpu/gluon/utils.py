"""Gluon utilities (``python/mxnet/gluon/utils.py``): split_and_load,
split_data, clip_global_norm."""
from __future__ import annotations

import math
from typing import List

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..ndarray import array as nd_array
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if size < num_slice:
        raise MXNetError("batch size %d < num_slice %d" % (size, num_slice))
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "data of shape %s cannot be evenly split into %d slices"
            % (data.shape, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * len(data.shape)
        idx[batch_axis] = slice(begin, end)
        slices.append(NDArray(data.data[tuple(idx)], ctx=data._ctx))
    return slices


def split_and_load(data, ctx_list, batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: List[NDArray], max_norm: float) -> float:
    """Rescale arrays so total L2 norm ≤ max_norm; returns the norm."""
    assert len(arrays) > 0
    total = 0.0
    for arr in arrays:
        total += float((arr * arr).sum().asscalar())
    total = math.sqrt(total)
    if not np.isfinite(total):
        import warnings

        warnings.warn("nan or inf found in gradients")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total
