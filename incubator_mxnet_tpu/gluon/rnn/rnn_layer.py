"""Gluon fused RNN layers (``python/mxnet/gluon/rnn/rnn_layer.py``): RNN /
LSTM / GRU over the fused scan-based ``RNN`` op (ops/rnn_ops.py)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(Block):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("layout must be TNC or NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        self._gates = gates

        self._params_per = []
        ng = gates * hidden_size
        for layer in range(num_layers):
            in_size = input_size if layer == 0 \
                else hidden_size * self._dir
            for direction in (["l", "r"] if bidirectional else ["l"]):
                self._params_per.append((
                    self.params.get(
                        "%s%d_i2h_weight" % (direction, layer),
                        shape=(ng, in_size),
                        init=i2h_weight_initializer,
                        allow_deferred_init=True),
                    self.params.get(
                        "%s%d_h2h_weight" % (direction, layer),
                        shape=(ng, hidden_size),
                        init=h2h_weight_initializer,
                        allow_deferred_init=True),
                    self.params.get(
                        "%s%d_i2h_bias" % (direction, layer),
                        shape=(ng,), init=i2h_bias_initializer,
                        allow_deferred_init=True),
                    self.params.get(
                        "%s%d_h2h_bias" % (direction, layer),
                        shape=(ng,), init=h2h_bias_initializer,
                        allow_deferred_init=True)))

    def state_info(self, batch_size=0):
        n = self._num_layers * self._dir
        info = [{"shape": (n, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (n, batch_size, self._hidden_size),
                         "__layout__": "LNC"})
        return info

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        func = func or nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            info = dict(info)
            info.update(kwargs)
            info.pop("__layout__", None)
            states.append(func(shape=info.pop("shape"), **info))
        return states

    def _finish_params(self, input_size):
        for i, tup in enumerate(self._params_per):
            layer = i // self._dir
            in_size = input_size if layer == 0 \
                else self._hidden_size * self._dir
            ng = self._gates * self._hidden_size
            shapes = [(ng, in_size), (ng, self._hidden_size), (ng,), (ng,)]
            for p, s in zip(tup, shapes):
                if p._deferred_init:
                    p._finish_deferred_init(s)

    def forward(self, inputs, states=None):
        from ... import ndarray as nd

        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, dim1=0, dim2=1)
        T, N, I = inputs.shape
        self._finish_params(I)
        skip_states = states is None
        if states is None:
            states = self.begin_state(N, ctx=inputs.context)
        # pack via recorded ops (Reshape+Concat) so autograd routes RNN
        # param grads back to each Parameter's grad buffer
        ctx = inputs.context
        flats = [tup[i].data(ctx).reshape((-1,))
                 for tup in self._params_per for i in (0, 1)]
        flats += [tup[i].data(ctx) for tup in self._params_per
                  for i in (2, 3)]
        params_nd = nd.Concat(*flats, dim=0)
        args = [inputs, params_nd] + list(states)
        outs = nd.RNN(*args, mode=self._mode,
                      state_size=self._hidden_size,
                      num_layers=self._num_layers,
                      bidirectional=self._dir == 2,
                      p=self._dropout, state_outputs=True)
        out = outs[0]
        out_states = list(outs[1:])
        if self._layout == "NTC":
            out = nd.swapaxes(out, dim1=0, dim2=1)
        if skip_states:
            return out
        return out, out_states


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero",
                 input_size=0, **kwargs):
        super().__init__("rnn_" + activation, hidden_size, num_layers,
                         layout, dropout, bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero",
                 **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero",
                 **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         **kwargs)
