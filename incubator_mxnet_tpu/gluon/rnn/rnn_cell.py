"""Gluon recurrent cells (``python/mxnet/gluon/rnn/rnn_cell.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            info.update(kwargs)
            states.append(func(name="%sbegin_state_%d"
                               % (self._prefix, self._init_counter),
                               **info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll over `length` steps (symbolic unrolling ≙ the reference;
        under jit XLA rolls this back into an efficient loop)."""
        from ... import ndarray as nd

        self.reset()
        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size,
                                           ctx=inputs.context)
        states = begin_state
        outputs = []
        for i in range(length):
            step = nd.slice_axis(inputs, axis=axis, begin=i, end=i + 1)
            step = nd.Reshape(step, shape=tuple(
                s for j, s in enumerate(step.shape) if j != axis))
            output, states = self(step, states)
            outputs.append(output)
        if merge_outputs is None or merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]

    def forward(self, inputs, states):
        from ... import ndarray as nd

        params = {k: self._param_data(p, inputs)
                  for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd, inputs, states, **params)

    def _param_data(self, p, inputs):
        from ..parameter import DeferredInitializationError

        try:
            return p.data(inputs.context)
        except DeferredInitializationError:
            if p.name.endswith("i2h_weight"):
                p._finish_deferred_init((self._hidden_size * self._gate_mult(),
                                         inputs.shape[-1]))
            else:
                raise
            return p.data(inputs.context)

    def _gate_mult(self):
        return 1


class LSTMCell(RNNCell):
    def __init__(self, hidden_size, input_size=0, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero"):
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _gate_mult(self):
        return 4

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RNNCell):
    def __init__(self, hidden_size, input_size=0, prefix=None, params=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zero", h2h_bias_initializer="zero"):
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _gate_mult(self):
        return 3

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_s[0] + h2h_s[0])
        update_gate = F.sigmoid(i2h_s[1] + h2h_s[1])
        next_h_tmp = F.tanh(i2h_s[2] + reset_gate * h2h_s[2])
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children:
            out.extend(cell.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, **kwargs):
        out = []
        for cell in self._children:
            out.extend(cell.begin_state(batch_size, **kwargs))
        return out

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + self._alias() + "_",
                         params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def forward(self, inputs, states):
        from ... import ndarray as nd

        if self._rate > 0:
            inputs = nd.Dropout(inputs, p=self._rate)
        return inputs, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def forward(self, inputs, states):
        from ... import autograd as ag
        from ... import ndarray as nd

        next_output, next_states = self.base_cell(inputs, states)
        if not ag.is_training():
            return next_output, next_states
        po, ps = self._zoneout_outputs, self._zoneout_states

        def mask(p, like):
            return nd.Dropout(nd.ones_like(like), p=p)

        prev = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(next_output)
        if po:
            m = mask(po, next_output)
            output = nd.where(m, next_output, prev)
        else:
            output = next_output
        if ps:
            states_out = [nd.where(mask(ps, ns), ns, s)
                          for ns, s in zip(next_states, states)]
        else:
            states_out = next_states
        self._prev_output = output
        return output, states_out


class ResidualCell(ModifierCell):
    def _alias(self):
        return "residual"

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children:
            out.extend(cell.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, **kwargs):
        out = []
        for cell in self._children:
            out.extend(cell.begin_state(batch_size, **kwargs))
        return out

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        from ... import ndarray as nd

        self.reset()
        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size,
                                           ctx=inputs.context)
        l_cell, r_cell = self._children
        n_l = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs,
                                        begin_state[:n_l], layout)
        rev = nd.reverse(inputs, axis=(axis,))
        r_out, r_states = r_cell.unroll(length, rev, begin_state[n_l:],
                                        layout)
        r_out = nd.reverse(r_out, axis=(axis,))
        outputs = nd.Concat(l_out, r_out, dim=2 if layout == "NTC" else 2)
        return outputs, l_states + r_states
