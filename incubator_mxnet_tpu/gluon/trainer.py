"""Gluon Trainer (``python/mxnet/gluon/trainer.py:26``): kvstore-backed
parameter updates over Parameter grad buffers."""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import kvstore as kvs, optimizer as opt_mod
from ..base import MXNetError
from ..model import _create_kvstore
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be list/dict of Parameters")
        self._params = []
        for p in params:
            if not isinstance(p, Parameter):
                raise MXNetError("non-Parameter in Trainer params")
            if p.grad_req != "null":
                self._params.append(p)
        optimizer_params = dict(optimizer_params or {})
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_arg = kvstore

    def _check_contexts(self):
        contexts = None
        for p in self._params:
            ctx = p.list_ctx()
            if contexts is not None and contexts != ctx:
                raise MXNetError("all Parameters must share contexts")
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None for Optimizer instance")
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_idx2name={
                                                 i: p.name for i, p in
                                                 param_dict.items()},
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        arg_arrays = {p.name: p.data(self._contexts[0])
                      for p in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore_arg, len(self._contexts), arg_arrays)
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        if kvstore:
            for i, param in enumerate(self._params):
                kvstore.init(i, param.data(self._contexts[0]))
                if update_on_kvstore:
                    kvstore.pull(i, param.list_data(), priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr: float):
        self._optimizer.lr = lr

    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """Aggregate grads across ctxs, update weights
        (reference ``trainer.py:116``)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kvstore:
                self._kvstore.push(i, param.list_grad(), priority=-i)
                if self._update_on_kvstore:
                    self._kvstore.pull(i, param.list_data(), priority=-i)
                    continue
                self._kvstore.pull(i, param.list_grad(), priority=-i)
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def save_states(self, fname: str):
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states())

    def load_states(self, fname: str):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                data = f.read()
            for upd in self._updaters:
                upd.set_states(data)
