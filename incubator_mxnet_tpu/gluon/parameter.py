"""Gluon Parameter / ParameterDict
(``python/mxnet/gluon/parameter.py:41,367``): deferred shape init, per-ctx
replicas, grad buffers, symbol bridging via ``var()``."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import autograd, initializer as init_mod, symbol as sym_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import zeros as nd_zeros
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter shape unknown until first forward."""


class Parameter:
    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype=np.float32, lr_mult: float = 1.0,
                 wd_mult: float = 1.0, init=None,
                 allow_deferred_init: bool = False,
                 differentiable: bool = True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        self._var = None
        self._data: Optional[Dict[Context, NDArray]] = None
        self._grad: Optional[Dict[Context, NDArray]] = None
        self._deferred_init = ()

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape,
                                                      self.dtype)

    # ------------------------------------------------------------------ init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False) -> None:
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = self.init if self.init is not None else default_init
        if self.shape is None or any(s == 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError("cannot initialize %s: shape unknown" %
                             self.name)
        self._finish_init(init, ctx)

    def _finish_init(self, init, ctx_list) -> None:
        data = nd_zeros(self.shape, dtype=self.dtype)
        initializer = init_mod.create(init) if isinstance(init, str) \
            else init
        initializer(init_mod.InitDesc(self.name), data)
        self._data = {}
        self._grad = {} if self.grad_req != "null" else None
        for c in ctx_list:
            self._data[c] = data.copyto(c)
            if self._grad is not None:
                g = nd_zeros(self.shape, ctx=c, dtype=self.dtype)
                self._data[c].grad = g
                self._data[c]._grad_req = self.grad_req
                autograd.mark_variables([self._data[c]], [g],
                                        self.grad_req)
                self._grad[c] = g
        self._deferred_init = ()

    def _finish_deferred_init(self, shape) -> None:
        if not self._deferred_init:
            raise DeferredInitializationError(
                "parameter %s not initialized" % self.name)
        self.shape = tuple(shape)
        init, ctx, default_init = self._deferred_init
        self._finish_init(init if init is not None else default_init, ctx)

    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "parameter %s deferred" % self.name)
            raise MXNetError(
                "parameter %s not initialized; call initialize()"
                % self.name)

    # ------------------------------------------------------------------ data
    def _ctx_key(self, ctx):
        ctx = ctx or current_context()
        if ctx in self._data:
            return ctx
        if len(self._data) == 1:
            return next(iter(self._data))
        raise MXNetError("parameter %s not on context %s" % (self.name, ctx))

    def data(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        return self._data[self._ctx_key(ctx)]

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError("parameter %s has grad_req=null" % self.name)
        return self._grad[self._ctx_key(ctx)]

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError("parameter %s has grad_req=null" % self.name)
        return list(self._grad.values())

    def list_ctx(self) -> List[Context]:
        if self._data is None and self._deferred_init:
            # deferred params know their target ctx before materializing
            return list(self._deferred_init[1])
        self._check_initialized()
        return list(self._data.keys())

    def set_data(self, data) -> None:
        self._check_initialized()
        for c, arr in self._data.items():
            if isinstance(data, NDArray):
                arr._set_data(data.data.astype(arr.dtype))
            else:
                arr[:] = np.asarray(data)

    def zero_grad(self) -> None:
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0.0

    def reset_ctx(self, ctx) -> None:
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = self._reduce()
            init_ctx = ctx
            self._data = None
            self._grad = None
            self.initialize(ctx=init_ctx, init=init_mod.Constant(0.0))
            self.set_data(data)

    def _reduce(self) -> NDArray:
        """Average over ctx replicas (gradient-sync safety)."""
        self._check_initialized()
        vals = list(self._data.values())
        if len(vals) == 1:
            return vals[0].copy()
        acc = vals[0].copyto(cpu())
        for v in vals[1:]:
            acc += v.copyto(cpu())
        return acc / len(vals)

    # ---------------------------------------------------------------- symbol
    def var(self):
        if self._var is None:
            self._var = sym_mod.Variable(
                self.name, shape=self.shape, dtype=self.dtype,
                lr_mult=self.lr_mult, wd_mult=self.wd_mult)
        return self._var

    def cast(self, dtype) -> None:
        self.dtype = dtype
        if self._data is None:
            return
        self._data = {c: v.astype(dtype) for c, v in self._data.items()}
        if self._grad is not None:
            new_grad = {c: g.astype(dtype) for c, g in self._grad.items()}
            for c in self._data:
                autograd.mark_variables([self._data[c]], [new_grad[c]],
                                        self.grad_req)
            self._grad = new_grad


class ParameterDict:
    def __init__(self, prefix: str = "", shared: "ParameterDict" = None):
        self._prefix = prefix
        self._params: Dict[str, Parameter] = {}
        self._shared = shared

    @property
    def prefix(self) -> str:
        return self._prefix

    def __repr__(self):
        return "ParameterDict '%s' (%s)" % (
            self._prefix, ", ".join(self._params))

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __contains__(self, key) -> bool:
        return key in self._params

    def get(self, name: str, **kwargs) -> Parameter:
        """Create-or-retrieve ``prefix+name``
        (reference ``ParameterDict.get``)."""
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            for k, v in kwargs.items():
                if getattr(param, k, None) is not None and v is not None \
                        and k == "shape":
                    if tuple(v) != tuple(param.shape or v):
                        raise MXNetError("shape mismatch for %s" % name)
            return param
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def update(self, other: "ParameterDict") -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        for p in self.values():
            p.initialize(init=None, ctx=ctx,
                         default_init=init or init_mod.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx) -> None:
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value) -> None:
        for p in self.values():
            setattr(p, name, value)

    def save(self, fname: str, strip_prefix: str = "") -> None:
        from ..ndarray import save as nd_save

        arg = {}
        for p in self.values():
            weight = p._reduce()
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = weight
        nd_save(fname, arg)

    def load(self, fname: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = "") -> None:
        from ..ndarray import load as nd_load

        loaded = nd_load(fname)
        loaded = {(restore_prefix + k.split(":", 1)[-1]): v
                  for k, v in loaded.items()}
        for name, p in self.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError("parameter %s missing in file" % name)
                continue
            if p._data is None and not p._deferred_init:
                p.shape = tuple(loaded[name].shape)
                p.initialize(ctx=ctx)
            elif p._deferred_init:
                p._finish_deferred_init(loaded[name].shape)
            p.set_data(loaded[name])
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError("extra parameters in file: %s"
                                 % sorted(extra))
