"""Gluon losses (``python/mxnet/gluon/loss.py``, 297 LoC)."""
from __future__ import annotations

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "FusedSoftmaxCEHead"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    # reshape_like works for both nd and symbolic tracing (a raw
    # `.shape` read would silently no-op on Symbols)
    return F.reshape_like(x, y)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            max_val = F.relu(-pred)
            loss = pred - pred * label + max_val + \
                F.log(F.exp(-max_val) + F.exp(-pred - max_val))
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label
                     + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class FusedSoftmaxCEHead(Loss):
    """Projection + softmax + cross-entropy as ONE chunked op — the
    gluon face of ``_contrib_SoftmaxXentHead`` (ops/nn.py): the
    (N, vocab) logits never materialize, so large-vocab LM heads train
    within memory (PERF.md §12).  Unlike ``SoftmaxCrossEntropyLoss``
    this block OWNS the output projection weight; call it on features
    (N, in_units) + sparse labels (N,) and it returns the mean loss.

    Not in the reference (its gluon predates fused heads); provided for
    parity between the symbolic (``models.transformer_lm(head='fused')``)
    and gluon frontends.

    Gradient convention: the op's custom VJP emits the analytic
    softmax-xent gradient scaled only by its ``grad_scale`` /
    ``normalization`` attrs — it ignores the incoming cotangent, so a
    ``weight`` or ``sample_weight`` here would rescale the reported
    loss value but NOT the gradients (unlike every other gluon Loss).
    Both are therefore rejected; fold a global weight into
    ``grad_scale`` on the op instead.
    """

    def __init__(self, vocab_size, in_units, weight_initializer=None,
                 weight=None, batch_axis=0, **kwargs):
        if weight is not None:
            raise MXNetError(
                "FusedSoftmaxCEHead does not support `weight`: the fused "
                "op's VJP ignores the incoming cotangent, so a weight "
                "would scale the loss value but not the gradients. Use "
                "the op's grad_scale attr instead.")
        super().__init__(None, batch_axis, **kwargs)
        self._vocab = vocab_size
        with self.name_scope():
            self.head_weight = self.params.get(
                "weight", shape=(vocab_size, in_units),
                init=weight_initializer)

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       head_weight=None):
        if sample_weight is not None:
            raise MXNetError(
                "FusedSoftmaxCEHead does not support `sample_weight`: "
                "the fused op's VJP ignores the incoming cotangent, so "
                "per-sample weights would affect only the reported loss "
                "value, never the gradients.")
        loss = F.SoftmaxXentHead(pred, head_weight, label,
                                 num_hidden=self._vocab)
        return F.mean(loss)
