"""Gluon Block / HybridBlock (``python/mxnet/gluon/block.py:115,283``).

TPU-native hybridize: instead of the reference's CachedOp over a composed
symbol, ``hybridize()`` traces ``hybrid_forward`` once with symbolic
placeholders into a Symbol DAG, lowers it through the shared
:mod:`..lowering`, and compiles with ``jax.jit`` — giving whole-block XLA
fusion (the Gluon analog of the executor's fused program).
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional

from .. import name as name_mod, symbol as sym_mod
from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = name_mod.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old_scope


class Block:
    """Base neural-network building block."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: List[Block] = []
        self._reg_params: Dict[str, Parameter] = {}

    def _alias(self) -> str:
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  (%d): %r" % (i, c)
                           for i, c in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pat = re.compile(select)
            ret.update(ParameterDict(self._params.prefix))
            for name, value in self.params.items():
                if pat.match(name):
                    ret._params[name] = value
        for child in self._children:
            child_params = child.collect_params(select)
            for name, value in child_params.items():
                ret._params[name] = value
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.register_child(value)
        elif isinstance(value, Parameter):
            if name in getattr(self, "_reg_params", {}):
                raise MXNetError("parameter %s already registered" % name)
            self._reg_params[name] = value
            self._params._params[value.name] = value
        super().__setattr__(name, value)

    def register_child(self, block: "Block") -> None:
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def save_params(self, fname: str) -> None:
        self.collect_params().save(fname, strip_prefix=self.prefix)

    def load_params(self, fname: str, ctx=None, allow_missing=False,
                    ignore_extra=False) -> None:
        self.collect_params().load(fname, ctx, allow_missing, ignore_extra,
                                   restore_prefix=self.prefix)

    def cast(self, dtype) -> None:
        for child in self._children:
            child.cast(dtype)
        for p in self.params.values():
            p.cast(dtype)

    def hybridize(self, active: bool = True) -> None:
        for child in self._children:
            child.hybridize(active)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


class HybridBlock(Block):
    """Block with a functional ``hybrid_forward(F, x, **params)`` that can
    run imperatively (F = mx.nd) or compiled (symbol trace + jit)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_fn = None
        self._cached_param_names = None

    def hybridize(self, active: bool = True) -> None:
        self._active = active
        self._cached_fn = None
        super().hybridize(active)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise MXNetError(
                "HybridBlock children must be HybridBlocks; found %s"
                % type(block))
        super().register_child(block)
        self._cached_fn = None

    def infer_shape(self, *args):
        """Deferred-shape resolution by symbolic tracing."""
        self._build_trace(args)

    # ------------------------------------------------------------- tracing
    def _trace_symbol(self, n_inputs: int):
        inputs = [sym_mod.Variable("data%d" % i if n_inputs > 1 else "data")
                  for i in range(n_inputs)]
        out = self._call_tree(sym_mod, *inputs)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        return inputs, out

    def _call_tree(self, F, *args):
        """Call hybrid_forward recursively with F=sym, feeding params as
        symbol variables."""
        params = {k: p.var() for k, p in self._reg_params.items()}
        return self.hybrid_forward(F, *args, **params)

    def _build_trace(self, args):
        """Infer deferred shapes + build the jitted cached fn."""
        inputs, out = self._trace_symbol(len(args))
        shapes = {}
        for iv, a in zip(inputs, args):
            shapes[iv.name] = a.shape
        arg_shapes, _, aux_shapes = out.infer_shape_partial(**shapes)
        arg_names = out.list_arguments()
        shape_of = dict(zip(arg_names, arg_shapes))
        shape_of.update(dict(zip(out.list_auxiliary_states(), aux_shapes)))
        # finish deferred param inits
        all_params = self.collect_params()
        for name, p in all_params.items():
            s = shape_of.get(name)
            if p._deferred_init and s is not None \
                    and all(d > 0 for d in s):
                p._finish_deferred_init(s)
        return inputs, out

    def _get_cached(self, args):
        if self._cached_fn is None:
            import jax

            inputs, out = self._build_trace(args)
            fwd = None
            from ..lowering import lower_symbol

            input_names = [iv.name for iv in inputs]
            aux_names = out.list_auxiliary_states()
            self._cached_out = out
            self._cached_input_names = input_names
            self._cached_aux_names = aux_names
            all_params = {p.name: p
                         for p in self.collect_params().values()}
            self._cached_params = all_params

            fwd_train = lower_symbol(out, True)
            fwd_test = lower_symbol(out, False)
            self._cached_fn = {True: jax.jit(fwd_train),
                               False: jax.jit(fwd_test)}
        return self._cached_fn

    def forward(self, *args):
        from .. import autograd as ag
        from .. import ndarray as nd
        from .. import random as _random

        if args and isinstance(args[0], sym_mod.Symbol):
            # symbolic composition (tracing pass / user symbol input)
            params = {k: p.var() for k, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, *args, **params)

        if not self._active:
            params = {}
            try:
                for k, p in self._reg_params.items():
                    params[k] = p.data(args[0].context if args else None)
            except DeferredInitializationError:
                self._build_trace(args)
                for k, p in self._reg_params.items():
                    params[k] = p.data(args[0].context if args else None)
            return self.hybrid_forward(nd, *args, **params)

        # hybrid path: jitted whole-block program
        try:
            fns = self._get_cached(args)
        except DeferredInitializationError:
            self._build_trace(args)
            fns = self._get_cached(args)
        is_train = ag.is_training()
        arg_vals = {}
        for name, a in zip(self._cached_input_names, args):
            arg_vals[name] = a.data
        for pname, p in self._cached_params.items():
            if pname not in self._cached_aux_names:
                arg_vals[pname] = p.data().data
        aux_vals = {n: self._cached_params[n].data().data
                    for n in self._cached_aux_names}
        if ag.is_recording():
            # fall back to imperative tape path for autograd correctness
            params = {k: p.data() for k, p in self._reg_params.items()}
            return self.hybrid_forward(nd, *args, **params)
        outs, new_aux = fns[is_train](arg_vals, aux_vals,
                                      _random.next_key())
        for n, v in new_aux.items():
            self._cached_params[n].data()._set_data(v)
        res = [NDArray(o) for o in outs]
        return res[0] if len(res) == 1 else res

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Wrap an arbitrary Symbol as a block
    (reference ``gluon.SymbolBlock``)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sym_out = outputs
        self._sym_inputs = [i.name for i in inputs]
        input_set = set(self._sym_inputs)
        for name in outputs.list_arguments():
            if name not in input_set:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            self.params.get(name, allow_deferred_init=True,
                            grad_req="null")

    def forward(self, *args):
        from .. import random as _random
        from ..lowering import lower_symbol
        from .. import autograd as ag

        shapes = dict(zip(self._sym_inputs, [a.shape for a in args]))
        arg_shapes, _, aux_shapes = \
            self._sym_out.infer_shape_partial(**shapes)
        names = self._sym_out.list_arguments()
        shape_of = dict(zip(names, arg_shapes))
        aux_names = self._sym_out.list_auxiliary_states()
        shape_of.update(dict(zip(aux_names, aux_shapes)))
        for name, p in self.params.items():
            if p._deferred_init and shape_of.get(name) is not None:
                p._finish_deferred_init(shape_of[name])
        fwd = lower_symbol(self._sym_out, ag.is_training())
        arg_vals = {n: a.data for n, a in zip(self._sym_inputs, args)}
        for name, p in self.params.items():
            if name not in aux_names:
                arg_vals[name] = p.data().data
        aux_vals = {n: self.params[n].data().data for n in aux_names}
        outs, new_aux = fwd(arg_vals, aux_vals, _random.next_key())
        for n, v in new_aux.items():
            self.params[n].data()._set_data(v)
        res = [NDArray(o) for o in outs]
        return res[0] if len(res) == 1 else res
