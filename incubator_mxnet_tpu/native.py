"""ctypes loader for the native (C++) runtime pieces.

The reference's recordio reader and batch loader are C++
(``dmlc-core/src/recordio.cc``, ``src/io/iter_batchloader.h``); here the
same pieces live in ``native/recordio_native.cc``, compiled on demand
with the host toolchain (pybind11 is not available in this image, so the
binding is a plain C ABI over ctypes — ctypes releases the GIL around
foreign calls, so pool threads overlap in the C code).

``lib()`` returns the loaded library or None (no toolchain, build
failure) — callers keep a pure-python fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "native",
                    "recordio_native.cc")
_SO = os.path.join(_HERE, "_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    # Compile to a private temp path and rename into place: rename is
    # atomic on POSIX, so concurrent builders (tools/launch.py local
    # mode, parallel test runs) never dlopen a half-written .so.
    tmp = "%s.%d" % (_SO, os.getpid())
    try:
        subprocess.check_call(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
             "-o", tmp, _SRC],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.rename(tmp, _SO)
        return True
    except (OSError, subprocess.CalledProcessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The native library, built+loaded lazily; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            cdll = ctypes.CDLL(_SO)
        except OSError:
            return None
        LL = ctypes.c_longlong
        cdll.tp_recordio_scan.restype = LL
        cdll.tp_recordio_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(LL), ctypes.POINTER(LL), LL]
        PP = ctypes.POINTER(ctypes.c_char_p)
        cdll.tp_assemble_chw_u8.restype = None
        cdll.tp_assemble_chw_u8.argtypes = [
            PP, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p]
        cdll.tp_assemble_chw_f32.restype = None
        cdll.tp_assemble_chw_f32.argtypes = [
            PP, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
        _lib = cdll
        return _lib


def recordio_scan(path: str):
    """-> (offsets, lengths) int64 arrays for every record in a .rec
    file, or None if the native library is unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    LL = ctypes.c_longlong
    cap = 1 << 16
    while True:
        offs = np.empty(cap, np.int64)
        lens = np.empty(cap, np.int64)
        n = cdll.tp_recordio_scan(
            path.encode(), offs.ctypes.data_as(ctypes.POINTER(LL)),
            lens.ctypes.data_as(ctypes.POINTER(LL)), cap)
        if n < 0:
            raise IOError("malformed recordio file %s" % path)
        if n <= cap:
            return offs[:n].copy(), lens[:n].copy()
        cap = int(n)


def assemble_batch(images, out: np.ndarray, mean=None, std=None) -> bool:
    """Transpose a list of HWC uint8 images into the CHW batch ``out``
    (uint8 or float32, with optional f32 mean/std normalize).  Returns
    False (caller falls back to numpy) if the native library is missing
    or shapes do not qualify."""
    cdll = lib()
    if cdll is None or not images:
        return False
    h, w, c = images[0].shape
    if out.shape[1:] != (c, h, w) or out.shape[0] < len(images) \
            or not out.flags.c_contiguous:
        return False
    for im in images:
        if im.shape != (h, w, c) or im.dtype != np.uint8 \
                or not im.flags.c_contiguous:
            return False
    ptrs = (ctypes.c_char_p * len(images))(
        *[im.ctypes.data_as(ctypes.c_char_p) for im in images])
    if out.dtype == np.uint8:
        cdll.tp_assemble_chw_u8(ptrs, len(images), h, w, c,
                                out.ctypes.data)
        return True
    if out.dtype == np.float32:
        m = np.ascontiguousarray(mean, np.float32) \
            if mean is not None else None
        s = np.ascontiguousarray(1.0 / np.asarray(std, np.float32)) \
            if std is not None else None
        cdll.tp_assemble_chw_f32(
            ptrs, len(images), h, w, c,
            m.ctypes.data if m is not None else None,
            s.ctypes.data if s is not None else None,
            out.ctypes.data)
        return True
    return False
