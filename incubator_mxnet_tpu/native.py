"""ctypes loader for the native (C++) runtime pieces.

The reference's recordio reader and batch loader are C++
(``dmlc-core/src/recordio.cc``, ``src/io/iter_batchloader.h``); here the
same pieces live in ``native/recordio_native.cc``, compiled on demand
with the host toolchain (pybind11 is not available in this image, so the
binding is a plain C ABI over ctypes — ctypes releases the GIL around
foreign calls, so pool threads overlap in the C code).

``lib()`` returns the loaded library or None (no toolchain, build
failure) — callers keep a pure-python fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "native",
                    "recordio_native.cc")
_SO = os.path.join(_HERE, "_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    # Compile to a private temp path and rename into place: rename is
    # atomic on POSIX, so concurrent builders (tools/launch.py local
    # mode, parallel test runs) never dlopen a half-written .so.
    tmp = "%s.%d" % (_SO, os.getpid())
    # -ljpeg: the decode stage links the system libjpeg; if that fails
    # (no jpeg dev files), fall back to building without the decoder
    for extra in (["-DTP_WITH_JPEG", "-ljpeg"], []):
        try:
            subprocess.check_call(
                ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                 "-o", tmp, _SRC] + extra,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            os.rename(tmp, _SO)
            return True
        except (OSError, subprocess.CalledProcessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return False


def lib() -> Optional[ctypes.CDLL]:
    """The native library, built+loaded lazily; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            cdll = ctypes.CDLL(_SO)
        except OSError:
            return None
        LL = ctypes.c_longlong
        cdll.tp_recordio_scan.restype = LL
        cdll.tp_recordio_scan.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(LL), ctypes.POINTER(LL), LL]
        PP = ctypes.POINTER(ctypes.c_char_p)
        cdll.tp_assemble_chw_u8.restype = None
        cdll.tp_assemble_chw_u8.argtypes = [
            PP, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p]
        cdll.tp_assemble_chw_f32.restype = None
        cdll.tp_assemble_chw_f32.argtypes = [
            PP, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
        if hasattr(cdll, "tp_decode_resize_crop"):
            cdll.tp_decode_resize_crop.restype = LL
            cdll.tp_decode_resize_crop.argtypes = [
                ctypes.c_char_p, LL, LL, LL, LL, LL, LL, LL,
                ctypes.c_void_p]
        if hasattr(cdll, "tp_transcode_jpeg"):
            cdll.tp_transcode_jpeg.restype = LL
            cdll.tp_transcode_jpeg.argtypes = [
                ctypes.c_char_p, LL, LL, LL, ctypes.c_void_p, LL]
        _lib = cdll
        return _lib


def recordio_scan(path: str):
    """-> (offsets, lengths) int64 arrays for every record in a .rec
    file, or None if the native library is unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    LL = ctypes.c_longlong
    cap = 1 << 16
    while True:
        offs = np.empty(cap, np.int64)
        lens = np.empty(cap, np.int64)
        n = cdll.tp_recordio_scan(
            path.encode(), offs.ctypes.data_as(ctypes.POINTER(LL)),
            lens.ctypes.data_as(ctypes.POINTER(LL)), cap)
        if n < 0:
            raise IOError("malformed recordio file %s" % path)
        if n <= cap:
            return offs[:n].copy(), lens[:n].copy()
        cap = int(n)


def assemble_batch(images, out: np.ndarray, mean=None, std=None) -> bool:
    """Transpose a list of HWC uint8 images into the CHW batch ``out``
    (uint8 or float32, with optional f32 mean/std normalize).  Returns
    False (caller falls back to numpy) if the native library is missing
    or shapes do not qualify."""
    cdll = lib()
    if cdll is None or not images:
        return False
    h, w, c = images[0].shape
    if out.shape[1:] != (c, h, w) or out.shape[0] < len(images) \
            or not out.flags.c_contiguous:
        return False
    for im in images:
        if im.shape != (h, w, c) or im.dtype != np.uint8 \
                or not im.flags.c_contiguous:
            return False
    ptrs = (ctypes.c_char_p * len(images))(
        *[im.ctypes.data_as(ctypes.c_char_p) for im in images])
    if out.dtype == np.uint8:
        cdll.tp_assemble_chw_u8(ptrs, len(images), h, w, c,
                                out.ctypes.data)
        return True
    if out.dtype == np.float32:
        m = np.ascontiguousarray(mean, np.float32) \
            if mean is not None else None
        s = np.ascontiguousarray(1.0 / np.asarray(std, np.float32)) \
            if std is not None else None
        cdll.tp_assemble_chw_f32(
            ptrs, len(images), h, w, c,
            m.ctypes.data if m is not None else None,
            s.ctypes.data if s is not None else None,
            out.ctypes.data)
        return True
    return False


def decode_resize_crop(buf: bytes, out_h: int, out_w: int, resize: int = 0,
                       crop_y: int = -1, crop_x: int = -1,
                       flip: bool = False):
    """JPEG bytes → HWC uint8 (out_h, out_w, 3) via the native decoder
    (libjpeg decode + bilinear shorter-side resize + crop + optional
    mirror in ONE GIL-free call — the reference's C++ decode stage,
    ``iter_image_recordio_2.cc``).  Returns None when the native
    decoder is unavailable, the buffer is not a decodable JPEG, or the
    crop does not fit (callers fall back to the cv2 path)."""
    cdll = lib()
    if cdll is None or not hasattr(cdll, "tp_decode_resize_crop"):
        return None
    out = np.empty((out_h, out_w, 3), np.uint8)
    rc = cdll.tp_decode_resize_crop(
        buf, len(buf), resize, out_h, out_w, crop_y, crop_x,
        1 if flip else 0, out.ctypes.data)
    if rc < 0:
        return None
    return out


def decoded_dims(buf: bytes, resize: int = 0):
    """Post-resize (h, w) the native decoder would produce for this
    JPEG, or None — lets callers draw random-crop offsets before the
    one-shot decode call.  Cheap: decodes only the header."""
    cdll = lib()
    if cdll is None or not hasattr(cdll, "tp_decode_resize_crop"):
        return None
    # header-only probe: ask for a 0x0 crop at (0,0); the decode still
    # runs, so probe+decode would double work — instead parse the SOF
    # marker here in python (few bytes; no pixel work)
    import struct as _struct

    i = 2
    n = len(buf)
    if n < 4 or buf[0:2] != b"\xff\xd8":
        return None
    h = w = None
    while i + 9 < n:
        if buf[i] != 0xFF:
            return None
        # JPEG allows any number of 0xFF fill bytes before a marker
        # code (ITU T.81 §B.1.1.2) — consume them or valid padded
        # files would silently lose the native fast path
        while i + 9 < n and buf[i + 1] == 0xFF:
            i += 1
        if i + 9 >= n:
            return None
        marker = buf[i + 1]
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            i += 2
            continue
        (seglen,) = _struct.unpack(">H", buf[i + 2:i + 4])
        if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
            h, w = _struct.unpack(">HH", buf[i + 5:i + 9])
            break
        i += 2 + seglen
    if not h or not w:
        return None
    if resize > 0 and min(h, w) != resize:
        if h < w:
            return resize, int(w * resize / h)
        return int(h * resize / w), resize
    return int(h), int(w)


def transcode_jpeg(buf: bytes, resize: int = 0, quality: int = 95):
    """Pack-time JPEG transcode (decode + bilinear shorter-side resize +
    re-encode) in one GIL-free native call — the im2rec C++ stage
    (reference ``tools/im2rec.cc``).  Returns the re-encoded bytes or
    None (native decoder unavailable / not a decodable JPEG)."""
    cdll = lib()
    if cdll is None or not hasattr(cdll, "tp_transcode_jpeg"):
        return None
    dims = decoded_dims(buf, resize)
    if dims is None:
        return None
    cap = dims[0] * dims[1] * 3 + (1 << 16)
    out = np.empty(cap, np.uint8)
    n = cdll.tp_transcode_jpeg(buf, len(buf), resize, quality,
                               out.ctypes.data, cap)
    if n < 0:
        return None
    return out[:n].tobytes()
