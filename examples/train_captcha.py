#!/usr/bin/env python
"""Multi-digit captcha recognition: one trunk, four softmax heads.

Reference family: ``example/captcha`` (``mxnet_captcha.R``): a captcha
image holds several digits; a shared conv trunk feeds per-position
classifier heads trained jointly, and the score that matters is the
EXACT match — every digit right at once.  This driver exercises the
multi-output training surface on the TPU-native stack: a
``mx.sym.Group`` of four ``SoftmaxOutput`` heads, ``Module`` with four
label names fed from one ``NDArrayIter`` label dict, the ``Accuracy``
metric zipping over (label, pred) pairs, and an exact-match eval.

Zero-egress: captchas are composed from the same fixed digit templates
``MNISTIter``'s synthetic fallback uses (four templates side by side
plus noise), so exact-match accuracy is checkable.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx

NUM_DIGITS = 4


def captcha_batches(n, seed=0):
    """(n, 1, 28, 28*4) images of 4 noisy template digits + (n, 4) labels."""
    templates = np.random.RandomState(42).rand(
        10, 28, 28).astype(np.float32)
    rng = np.random.RandomState(seed)
    rng.rand(8192)  # warm MT19937 (io.py's synthetic-MNIST idiom)
    labels = rng.randint(0, 10, (n, NUM_DIGITS))
    img = templates[labels]                       # (n, 4, 28, 28)
    img = img.transpose(0, 2, 1, 3).reshape(n, 28, 28 * NUM_DIGITS)
    img = img + rng.randn(*img.shape).astype(np.float32) * 0.3
    return np.clip(img, 0, 1)[:, None], labels.astype(np.float32)


def captcha_symbol():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=16,
                            name="conv1")
    p1 = mx.sym.Pooling(mx.sym.Activation(c1, act_type="relu"),
                        pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=32,
                            name="conv2")
    p2 = mx.sym.Pooling(mx.sym.Activation(c2, act_type="relu"),
                        pool_type="max", kernel=(2, 2), stride=(2, 2))
    trunk = mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Flatten(p2), num_hidden=128,
                              name="fc_trunk"), act_type="relu")
    heads = []
    for i in range(NUM_DIGITS):
        fc = mx.sym.FullyConnected(trunk, num_hidden=10,
                                   name="digit%d" % i)
        heads.append(mx.sym.SoftmaxOutput(
            fc, label=mx.sym.Variable("digit%d_label" % i),
            name="softmax%d" % i))
    return mx.sym.Group(heads)


def exact_match(mod, data, labels, batch_size):
    """Fraction of captchas with ALL digits predicted correctly."""
    hits, total = 0, 0
    for s in range(0, len(data) - batch_size + 1, batch_size):
        mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(data[s:s + batch_size])]), is_train=False)
        preds = [o.asnumpy().argmax(axis=1)
                 for o in mod.get_outputs()]
        want = labels[s:s + batch_size].astype(np.int64)
        ok = np.ones(batch_size, bool)
        for i in range(NUM_DIGITS):
            ok &= preds[i] == want[:, i]
        hits += int(ok.sum())
        total += batch_size
    return hits / float(total)


def main():
    p = argparse.ArgumentParser(
        description="multi-digit captcha (4 softmax heads on one trunk)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=12)
    # NB: all four heads' gradients sum into the shared trunk, so the
    # workable lr is ~NUM_DIGITS x smaller than the single-head task's
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    if args.num_examples < args.batch_size:
        p.error("--num-examples must be >= --batch-size")
    mx.random.seed(0)
    X, Y = captcha_batches(args.num_examples)
    label_dict = {"digit%d_label" % i: Y[:, i]
                  for i in range(NUM_DIGITS)}
    it = mx.io.NDArrayIter({"data": X}, label_dict,
                           batch_size=args.batch_size, shuffle=True)
    mod = mx.mod.Module(captcha_symbol(), data_names=("data",),
                        label_names=tuple(sorted(label_dict)),
                        context=mx.cpu())
    mod.fit(it, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": 0.9, "wd": 1e-4},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            eval_metric="acc")

    acc = exact_match(mod, X, Y, args.batch_size)
    logging.info("exact-match accuracy=%.4f (all %d digits)",
                 acc, NUM_DIGITS)
    assert acc > 0.8, "captcha exact-match too low: %.4f" % acc
    print("done")


if __name__ == "__main__":
    main()
