#!/usr/bin/env python
"""Sequence labeling with CTC loss (warp-ctc example family)::

    python examples/train_ctc_seq.py --num-epochs 15

Port of the reference warpctc/OCR example family (``example/warpctc``):
an LSTM reads a feature sequence and emits per-timestep class logits;
``CTCLoss`` aligns the unsegmented label sequence (blank = 0, labels
0-padded) — the only driver exercising the CTC alignment machinery in
a trained model.

Synthetic task, OCR-shaped: each "image" is a sequence of T=20 glyph
feature vectors rendering 3-5 digits with variable-width strokes and
inter-glyph gaps; the model must emit the digit string.  Decoded with
best-path (collapse repeats, drop blanks); sequence accuracy is exact-
match, so learning is verifiable end to end.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def ctc_net(seq_len, feat, hidden, classes):
    """LSTM → per-step FC → CTCLoss (data (T, N, C) warp-ctc layout)."""
    data = mx.sym.Variable("data")           # (N, T, F) batch-major in
    label = mx.sym.Variable("label")         # (N, L) 0-padded
    # the RNN op is TIME-MAJOR (TNC, reference RNN layout): transpose
    # first or the recurrence would scan across the BATCH axis
    x = mx.sym.transpose(data, axes=(1, 0, 2), name="tnf")  # (T, N, F)
    x = mx.sym.RNN(x, state_size=hidden, num_layers=1, mode="lstm",
                   name="lstm")              # (T, N, H)
    x = mx.sym.Reshape(x, shape=(-1, hidden), name="steps_flat")
    x = mx.sym.FullyConnected(x, num_hidden=classes, name="cls")
    x = mx.sym.Reshape(x, shape=(seq_len, -1, classes),
                       name="tnc")           # (T, N, C)
    loss = mx.sym.CTCLoss(x, label, name="ctc")
    # Group: the loss trains (MakeLoss semantics via ones-cotangent);
    # the grad-blocked logits ride along for decoding
    return mx.sym.Group([mx.sym.make_loss(loss, name="ctc_loss"),
                         mx.sym.BlockGrad(x, name="logits")])


def render(rng, digits, seq_len, feat):
    """Digit string → glyph feature sequence with jittered widths/gaps.
    Glyph code for digit d is a fixed random vector (the 'font').
    Returns (sequence, rendered_digits): a digit that did not fit is
    DROPPED from the label too, so every label is achievable."""
    seq = np.zeros((seq_len, feat), np.float32)
    t = rng.randint(0, 2)
    rendered = []
    for d in digits:
        if t >= seq_len:
            break
        w = rng.randint(2, 4)                  # stroke width 2-3 steps
        drawn = 0
        for _ in range(w):
            if t >= seq_len:
                break
            seq[t] = FONT[d]
            t += 1
            drawn += 1
        if drawn:
            rendered.append(d)
        t += rng.randint(1, 3)                 # gap 1-2 steps
    seq += 0.1 * rng.randn(*seq.shape).astype(np.float32)
    return seq, rendered


def best_path_decode(logits):
    """(T, N, C) → list of label lists: argmax, collapse, drop blanks."""
    ids = logits.argmax(-1)                    # (T, N)
    out = []
    for n in range(ids.shape[1]):
        prev, dec = 0, []
        for c in ids[:, n]:
            if c != prev and c != 0:
                dec.append(int(c))
            prev = c
        out.append(dec)
    return out


def main():
    ap = argparse.ArgumentParser(description="CTC sequence labeling")
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=48)
    ap.add_argument("--max-label", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--num-batches", type=int, default=16)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.max_label < 3:
        ap.error("--max-label must be >= 3 (sequences draw 3..max "
                 "digits)")

    global FONT
    rng = np.random.RandomState(0)
    # classes: blank 0 + digits 1..10
    FONT = {d: rng.randn(args.feat).astype(np.float32)
            for d in range(1, 11)}
    classes = 11

    B, T, L = args.batch_size, args.seq_len, args.max_label
    data, labels = [], []
    for _ in range(args.num_batches * B):
        n = rng.randint(3, L + 1)
        digs = list(rng.randint(1, 11, n))
        seq, rendered = render(rng, digs, T, args.feat)
        data.append(seq)
        labels.append(rendered + [0] * (L - len(rendered)))
    data = np.stack(data)
    labels = np.asarray(labels, np.float32)

    mx.random.seed(0)
    net = ctc_net(T, args.feat, args.hidden, classes)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("label",))
    mod.bind(data_shapes=[("data", (B, T, args.feat))],
             label_shapes=[("label", (B, L))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    from incubator_mxnet_tpu.io import DataBatch

    for epoch in range(args.num_epochs):
        tot_loss = correct = total = 0.0
        for b in range(args.num_batches):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch([mx.nd.array(data[sl])],
                                           [mx.nd.array(labels[sl])]))
            mod.update()
            outs = mod.get_outputs()
            tot_loss += float(outs[0].asnumpy().mean())
            decoded = best_path_decode(outs[1].asnumpy())
            for n, dec in enumerate(decoded):
                want = [int(v) for v in labels[sl][n] if v != 0]
                correct += dec == want
                total += 1
        logging.info("Epoch[%d] ctc-loss=%.3f seq-accuracy=%.4f",
                     epoch, tot_loss / args.num_batches,
                     correct / total)
    assert correct / total > 0.7, correct / total
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
