#!/usr/bin/env python
"""Sort a token sequence with a bidirectional LSTM (reference
``example/bi-lstm-sort``)::

    python examples/train_bi_lstm_sort.py --num-epochs 6

The model reads a sequence of tokens and must emit the same tokens in
sorted order — solvable only with context from BOTH directions, which
is what makes it the classic BidirectionalCell exerciser.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402


def sort_symbol(vocab, seq_len, embed=32, hidden=64):
    """Embed → BidirectionalCell(LSTM, LSTM) unroll → per-step FC →
    softmax over the sorted-token targets (reference sort_io/lstm
    pipeline)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                           name="embed")
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(hidden, prefix="l_"),
        mx.rnn.LSTMCell(hidden, prefix="r_"))
    outputs, _ = cell.unroll(seq_len, inputs=emb, layout="NTC",
                             merge_outputs=True)
    out = mx.sym.Reshape(outputs, shape=(-1, 2 * hidden),
                         name="flatten_steps")
    fc = mx.sym.FullyConnected(out, num_hidden=vocab, name="cls")
    lab = mx.sym.Reshape(label, shape=(-1,), name="label_flat")
    return mx.sym.SoftmaxOutput(fc, lab, name="softmax")


def main():
    ap = argparse.ArgumentParser(description="bi-LSTM sort")
    ap.add_argument("--vocab-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    toks = rng.randint(0, args.vocab_size,
                       (args.num_examples, args.seq_len))
    targets = np.sort(toks, axis=1).astype(np.float32)
    toks = toks.astype(np.float32)

    net = sort_symbol(args.vocab_size, args.seq_len)
    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, args.seq_len))],
             label_shapes=[("softmax_label", (B, args.seq_len))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    n_batches = args.num_examples // B
    if n_batches == 0:
        ap.error("--num-examples must be >= --batch-size")
    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(n_batches):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch(
                [mx.nd.array(toks[sl])], [mx.nd.array(targets[sl])]))
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(1)
            correct += (pred == targets[sl].reshape(-1)).sum()
            total += pred.size
        acc = correct / total
        logging.info("Epoch[%d] per-token sort accuracy=%.3f", epoch,
                     acc)
    print("final-acc=%.3f" % acc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
