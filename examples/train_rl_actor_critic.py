#!/usr/bin/env python
"""Parallel advantage actor-critic on a built-in CartPole.

Reference family: ``example/reinforcement-learning/parallel_actor_critic``
(``train.py``/``model.py``): trajectories from many environments stepped
in ONE process are batched together, advantages come from Generalized
Advantage Estimation, and a single forward/backward updates a shared
policy+value net.  This driver reproduces that algorithm on the
TPU-native imperative stack (gluon ``Block`` + ``autograd`` + ``Trainer``
— where the reference hand-injects the policy gradient through
``Module.backward``, autograd differentiates the actual A2C loss).

Zero-egress: the OpenAI-gym dependency is replaced by a vectorized
numpy CartPole (the classic cart-pole dynamics; random policy survives
~20 steps, a learned one 10x that), so learning progress is checkable.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon


class CartPoleVec:
    """``num_envs`` independent cart-poles stepped as one batch.

    Standard dynamics (gravity 9.8, pole half-length 0.5, force 10,
    dt 0.02); an episode ends when ``|x| > 2.4``, ``|theta| > 12 deg``,
    or after ``horizon`` steps, and that env auto-resets.
    """

    def __init__(self, num_envs, horizon=200, seed=0):
        self.n = num_envs
        self.horizon = horizon
        self.rng = np.random.RandomState(seed)
        self.state = self._fresh(num_envs)
        self.steps = np.zeros(num_envs, np.int64)

    def _fresh(self, n):
        return self.rng.uniform(-0.05, 0.05, size=(n, 4))

    def step(self, action):
        """action: (n,) in {0,1}.  Returns (obs, reward, done)."""
        x, x_dot, th, th_dot = self.state.T
        force = np.where(action == 1, 10.0, -10.0)
        cos, sin = np.cos(th), np.sin(th)
        pm = 0.1  # pole mass
        total_m = 1.1  # cart + pole
        pl = 0.5  # half pole length
        tmp = (force + pm * pl * th_dot ** 2 * sin) / total_m
        th_acc = (9.8 * sin - cos * tmp) / \
            (pl * (4.0 / 3.0 - pm * cos ** 2 / total_m))
        x_acc = tmp - pm * pl * th_acc * cos / total_m
        dt = 0.02
        self.state = np.stack(
            [x + dt * x_dot, x_dot + dt * x_acc,
             th + dt * th_dot, th_dot + dt * th_acc], axis=1)
        self.steps += 1
        done = (np.abs(self.state[:, 0]) > 2.4) \
            | (np.abs(self.state[:, 2]) > 12 * np.pi / 180) \
            | (self.steps >= self.horizon)
        reward = np.ones(self.n, np.float32)
        if done.any():
            self.state[done] = self._fresh(int(done.sum()))
            self.steps[done] = 0
        return self.state.astype(np.float32), reward, done


class ActorCritic(gluon.Block):
    """Shared trunk, softmax policy head + scalar value head."""

    def __init__(self, num_hidden, num_actions, **kw):
        super(ActorCritic, self).__init__(**kw)
        with self.name_scope():
            self.trunk = gluon.nn.Sequential()
            with self.trunk.name_scope():
                self.trunk.add(gluon.nn.Dense(num_hidden,
                                              activation="relu"))
            self.policy = gluon.nn.Dense(num_actions)
            self.value = gluon.nn.Dense(1)

    def forward(self, obs):
        h = self.trunk(obs)
        return self.policy(h), self.value(h)


def gae(rewards, values, dones, last_value, gamma, lam):
    """Generalized Advantage Estimation over a (T, E) rollout."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    running = np.zeros(rewards.shape[1], np.float32)
    next_v = last_value
    for t in range(T - 1, -1, -1):
        not_done = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_v * not_done - values[t]
        running = delta + gamma * lam * not_done * running
        adv[t] = running
        next_v = values[t]
    return adv


def main():
    p = argparse.ArgumentParser(
        description="parallel advantage actor-critic (built-in CartPole)")
    p.add_argument("--num-envs", type=int, default=16)
    p.add_argument("--t-max", type=int, default=20,
                   help="rollout length per update")
    p.add_argument("--updates", type=int, default=150)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=7e-3)
    p.add_argument("--gamma", type=float, default=0.99)
    p.add_argument("--gae-lambda", type=float, default=0.95)
    p.add_argument("--vf-coef", type=float, default=0.5)
    p.add_argument("--ent-coef", type=float, default=0.01)
    p.add_argument("--disp", type=int, default=25)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed + 1)
    envs = CartPoleVec(args.num_envs, seed=args.seed + 2)
    net = ActorCritic(args.num_hidden, 2)
    net.collect_params().initialize(
        mx.initializer.Xavier(factor_type="in", magnitude=2.34))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    import collections

    obs = envs.state.astype(np.float32)
    ep_len = np.zeros(args.num_envs, np.float64)
    E, T = args.num_envs, args.t_max
    finished = collections.deque(maxlen=10 * E)  # completed episodes

    for update in range(1, args.updates + 1):
        obs_buf = np.zeros((T, E, 4), np.float32)
        act_buf = np.zeros((T, E), np.int64)
        rew_buf = np.zeros((T, E), np.float32)
        done_buf = np.zeros((T, E), np.float32)
        val_buf = np.zeros((T, E), np.float32)

        for t in range(T):
            logits, value = net(mx.nd.array(obs))
            probs = mx.nd.softmax(logits).asnumpy()
            cdf = probs.cumsum(axis=1)
            cdf /= cdf[:, -1:]
            action = (rng.random_sample((E, 1)) < cdf).argmax(axis=1)
            obs_buf[t], act_buf[t] = obs, action
            val_buf[t] = value.asnumpy()[:, 0]
            obs, rew_buf[t], done = envs.step(action)
            done_buf[t] = done
            ep_len += 1
            if done.any():
                finished.extend(ep_len[done].tolist())
                ep_len[done] = 0

        _, last_v = net(mx.nd.array(obs))
        adv = gae(rew_buf, val_buf, done_buf,
                  last_v.asnumpy()[:, 0], args.gamma, args.gae_lambda)
        returns = adv + val_buf
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        flat_obs = mx.nd.array(obs_buf.reshape(E * T, 4))
        flat_act = mx.nd.array(act_buf.reshape(-1).astype(np.float32))
        flat_adv = mx.nd.array(adv.reshape(-1))
        flat_ret = mx.nd.array(returns.reshape(-1))
        with autograd.record():
            logits, value = net(flat_obs)
            logp = mx.nd.log_softmax(logits)
            chosen = mx.nd.pick(logp, flat_act, axis=1)
            pg = -mx.nd.mean(chosen * flat_adv)
            vf = mx.nd.mean(
                mx.nd.square(value.reshape((-1,)) - flat_ret))
            ent = -mx.nd.mean(mx.nd.sum(logp * mx.nd.exp(logp), axis=1))
            loss = pg + args.vf_coef * vf - args.ent_coef * ent
        loss.backward()
        trainer.step(1)

        if update % args.disp == 0:
            recent = list(finished)
            mean_len = float(np.mean(recent)) if recent else float("nan")
            logging.info(
                "update %d mean-episode-length=%.1f loss=%.4f "
                "entropy=%.3f", update, mean_len,
                float(loss.asnumpy()), float(ent.asnumpy()))

    recent = list(finished)
    logging.info("final mean-episode-length=%.1f",
                 float(np.mean(recent)) if recent else float("nan"))
    print("done")


if __name__ == "__main__":
    main()
