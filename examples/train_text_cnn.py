#!/usr/bin/env python
"""Text classification with a Kim-style CNN (reference
``example/cnn_text_classification``)::

    python examples/train_text_cnn.py --num-epochs 4

Embedding → parallel convolutions over n-gram windows → max-pool →
concat → dropout → softmax.  Synthetic task: a sentence is positive iff
it contains the token bigram (3, 7) — learnable only through the
n-gram filters.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402


def text_cnn_symbol(vocab_size, seq_len, embed=32, filters=(2, 3, 4),
                    num_filter=16, num_classes=2, dropout=0.5):
    """Reference ``text_cnn.py`` sym_gen."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=vocab_size, output_dim=embed,
                           name="embed")
    # (B, S, E) -> (B, 1, S, E): conv over the n-gram (time) axis
    x = mx.sym.Reshape(emb, shape=(0, 1, seq_len, embed), name="to_nchw")
    pooled = []
    for f in filters:
        c = mx.sym.Convolution(x, kernel=(f, embed),
                               num_filter=num_filter,
                               name="conv%d" % f)
        c = mx.sym.Activation(c, act_type="relu", name="relu%d" % f)
        p = mx.sym.Pooling(c, pool_type="max",
                           kernel=(seq_len - f + 1, 1),
                           name="pool%d" % f)
        pooled.append(p)
    h = mx.sym.Concat(*pooled, dim=1, name="concat")
    h = mx.sym.Flatten(h)
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout, name="drop")
    fc = mx.sym.FullyConnected(h, num_hidden=num_classes, name="cls")
    return mx.sym.SoftmaxOutput(fc, label, name="softmax")


def make_data(rng, n, vocab, seq_len):
    toks = rng.randint(0, vocab, (n, seq_len))
    labels = np.zeros(n, np.float32)
    for i in range(n):
        if rng.rand() < 0.5:   # plant the positive bigram
            pos = rng.randint(0, seq_len - 1)
            toks[i, pos], toks[i, pos + 1] = 3, 7
        has = any(toks[i, j] == 3 and toks[i, j + 1] == 7
                  for j in range(seq_len - 1))
        labels[i] = float(has)
    return toks.astype(np.float32), labels


def main():
    ap = argparse.ArgumentParser(description="Train a text CNN")
    ap.add_argument("--vocab-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    toks, labels = make_data(rng, args.num_examples, args.vocab_size,
                             args.seq_len)
    net = text_cnn_symbol(args.vocab_size, args.seq_len)
    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, args.seq_len))],
             label_shapes=[("softmax_label", (B,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    n_batches = args.num_examples // B
    if n_batches == 0:
        ap.error("--num-examples (%d) must be >= --batch-size (%d)"
                 % (args.num_examples, B))
    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = 0
        for b in range(n_batches):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch(
                [mx.nd.array(toks[sl])], [mx.nd.array(labels[sl])]))
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(1)
            correct += (pred == labels[sl]).sum()
        acc = correct / (n_batches * B)
        logging.info("Epoch[%d] Train-accuracy=%.3f", epoch, acc)
    print("final-acc=%.3f" % acc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
