#!/usr/bin/env python
"""Fully-convolutional semantic segmentation (FCN)::

    python examples/train_fcn_seg.py --num-epochs 6

Port of the reference FCN example family (``example/fcn-xs``): a conv
encoder downsamples, a ``Deconvolution`` (transposed conv) upsamples
back to input resolution, and per-pixel classification goes through
``SoftmaxOutput(multi_output=True)`` — the surface no classification
driver touches (upsampling kernels + the spatial softmax axis).

The synthetic task segments images of random bright rectangles and
disks on a dark background into {background, rectangle, disk} — fully
learnable, so pixel accuracy is a real correctness check.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def fcn_net(num_classes=3):
    """conv(s2) → conv(s2) → conv → 4× Deconvolution upsample →
    1×1 score conv → per-pixel softmax (reference fcn-xs topology,
    shrunk)."""
    x = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")   # (B, H*W) int classes
    x = mx.sym.Convolution(x, num_filter=16, kernel=(5, 5),
                           stride=(2, 2), pad=(2, 2), name="c1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.Convolution(x, num_filter=32, kernel=(3, 3),
                           stride=(2, 2), pad=(1, 1), name="c2")
    x = mx.sym.Activation(x, act_type="relu", name="r2")
    x = mx.sym.Convolution(x, num_filter=32, kernel=(3, 3),
                           pad=(1, 1), name="c3")
    x = mx.sym.Activation(x, act_type="relu", name="r3")
    # 4x bilinear-style learnable upsample back to full resolution
    x = mx.sym.Deconvolution(x, num_filter=16, kernel=(8, 8),
                             stride=(4, 4), pad=(2, 2), name="up4")
    x = mx.sym.Activation(x, act_type="relu", name="r4")
    score = mx.sym.Convolution(x, num_filter=num_classes,
                               kernel=(1, 1), name="score")
    score = mx.sym.Reshape(score, shape=(0, num_classes, -1),
                           name="score_flat")
    return mx.sym.SoftmaxOutput(score, label, multi_output=True,
                                name="softmax")


def make_images(rng, n, size):
    imgs = np.zeros((n, 1, size, size), np.float32)
    masks = np.zeros((n, size, size), np.float32)
    yy, xx = np.mgrid[0:size, 0:size]
    for i in range(n):
        for _ in range(2):
            kind = rng.randint(2)
            cy, cx = rng.randint(8, size - 8, 2)
            r = rng.randint(4, 8)
            if kind == 0:                      # rectangle → class 1
                sel = (abs(yy - cy) < r) & (abs(xx - cx) < r)
                cls = 1
            else:                              # disk → class 2
                sel = (yy - cy) ** 2 + (xx - cx) ** 2 < r * r
                cls = 2
            imgs[i, 0][sel] = 0.5 + 0.5 * rng.rand()
            masks[i][sel] = cls
    imgs += 0.05 * rng.randn(*imgs.shape).astype(np.float32)
    return imgs, masks.reshape(n, -1)


def main():
    ap = argparse.ArgumentParser(description="FCN segmentation")
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.size < 20:
        ap.error("--size must be >= 20 (shapes are drawn with centers "
                 "in [8, size-8))")

    B, S = args.batch_size, args.size
    rng = np.random.RandomState(0)
    imgs, masks = make_images(rng, args.num_batches * B, S)

    mx.random.seed(0)
    net = fcn_net()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, 1, S, S))],
             label_shapes=[("softmax_label", (B, S * S))])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    from incubator_mxnet_tpu.io import DataBatch

    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(args.num_batches):
            sl = slice(b * B, (b + 1) * B)
            batch = DataBatch([mx.nd.array(imgs[sl])],
                              [mx.nd.array(masks[sl])])
            mod.forward_backward(batch)
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(1)  # (B, H*W)
            correct += (pred == masks[sl]).sum()
            total += pred.size
        logging.info("Epoch[%d] pixel-accuracy=%.4f", epoch,
                     correct / total)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
