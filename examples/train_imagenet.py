#!/usr/bin/env python
"""Train on imagenet-class data (BASELINE configs 2/5; reference
``example/image-classification/train_imagenet.py``)::

    # synthetic perf run (the reference's --benchmark 1)
    python examples/train_imagenet.py --network resnet --num-layers 50 \
        --benchmark 1 --batch-size 256 --num-epochs 1

    # real RecordIO data (packed with tools/im2rec.py)
    python examples/train_imagenet.py --network resnet --num-layers 50 \
        --data-train train.rec --data-val val.rec

    # distributed (under tools/launch.py)
    python tools/launch.py -n 4 python examples/train_imagenet.py \
        --network resnet --num-layers 50 --benchmark 1 --kv-store dist_sync
"""
import argparse
import logging

from common import data, fit

import incubator_mxnet_tpu as mx


def get_network(args):
    image_shape = tuple(int(d) for d in args.image_shape.split(","))
    name = args.network
    if name == "resnet":
        return mx.models.resnet(num_layers=args.num_layers or 50,
                                num_classes=args.num_classes,
                                image_shape=image_shape,
                                dtype=args.dtype)
    if name == "vgg":
        return mx.models.vgg(num_layers=args.num_layers or 16,
                             num_classes=args.num_classes)
    if name == "alexnet":
        return mx.models.alexnet(num_classes=args.num_classes)
    if name in ("inception-bn", "inception_bn"):
        return mx.models.inception_bn(num_classes=args.num_classes)
    return mx.models.get_symbol(name, num_classes=args.num_classes,
                                image_shape=image_shape)


def main():
    parser = argparse.ArgumentParser(
        description="train imagenet-class networks",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_aug_args(parser)
    parser.set_defaults(network="resnet", num_layers=50,
                        num_classes=1000, num_examples=1281167,
                        image_shape="3,224,224",
                        batch_size=128, num_epochs=80,
                        lr=0.1, lr_step_epochs="30,60,80",
                        dtype="float32")
    args = parser.parse_args()
    fit.fit(args, get_network(args), data.get_image_iters)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
