#!/usr/bin/env python
"""Bayesian neural network via SGLD posterior sampling::

    python examples/train_bayesian_sgld.py --num-epochs 30

Port of the reference Bayesian-methods example family
(``example/bayesian-methods``): stochastic gradient Langevin dynamics
— the ``SGLD`` optimizer's gradient step plus N(0, lr) injected noise —
turns SGD into an MCMC sampler over the posterior.  After a burn-in,
parameter snapshots ARE posterior samples; averaging their predictions
gives the Bayesian model average, which must match or beat the last
single sample on held-out data.

Exercises the surface no other driver touches: the SGLD optimizer
(weight-decay-as-Gaussian-prior, per-update noise through the global
``mx.random`` stream) and multi-snapshot Module prediction.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def net(hidden=16, classes=2):
    x = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(x, num_hidden=hidden, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.FullyConnected(x, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                                name="softmax")


def make_data(rng, n, noise=0.25):
    """Two interleaved half-moons — nonlinear, slightly noisy."""
    t = rng.rand(n) * np.pi
    flip = rng.randint(0, 2, n)
    x = np.stack([np.cos(t) + flip * 1.0 - 0.5,
                  np.sin(t) * (1 - 2 * flip) + flip * 0.25], 1)
    x += noise * rng.randn(n, 2)
    return x.astype(np.float32), flip.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description="SGLD Bayesian NN")
    ap.add_argument("--num-train", type=int, default=512)
    ap.add_argument("--num-test", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--burn-in", type=int, default=15)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--wd", type=float, default=1e-3,
                    help="Gaussian prior precision (SGLD's weight decay)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.num_epochs <= args.burn_in:
        ap.error("--num-epochs must exceed --burn-in (no posterior "
                 "samples would be collected)")

    rng = np.random.RandomState(0)
    xtr, ytr = make_data(rng, args.num_train)
    xte, yte = make_data(rng, args.num_test)

    mx.random.seed(0)
    B = args.batch_size
    mod = mx.mod.Module(net(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, 2))],
             label_shapes=[("softmax_label", (B,))])
    mod.init_params(mx.initializer.Xavier())
    # Welling & Teh: the SGLD drift is lr/2 * (∇log prior + N/B * minibatch
    # log-lik gradient) + N(0, lr).  SoftmaxOutput's grad is the minibatch
    # MEAN, so without the N/B rescale the likelihood term is B/N times too
    # weak relative to the injected noise and the chain never concentrates.
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params={
                           "learning_rate": args.lr,
                           "rescale_grad": args.num_train / args.batch_size,
                           "wd": args.wd})
    from incubator_mxnet_tpu.io import DataBatch

    def predict_probs(x):
        # the library iterator pads the last batch and predict()
        # strips it — no hand-rolled batching
        it = mx.io.NDArrayIter(x, batch_size=B,
                               last_batch_handle="pad")
        return mod.predict(it).asnumpy()[:len(x)]

    posterior = np.zeros((args.num_test, 2), np.float64)
    sample_accs = []
    n_samples = 0
    nb = args.num_train // B
    for epoch in range(args.num_epochs):
        perm = rng.permutation(args.num_train)
        for b in range(nb):
            sl = perm[b * B:(b + 1) * B]
            mod.forward_backward(DataBatch([mx.nd.array(xtr[sl])],
                                           [mx.nd.array(ytr[sl])]))
            mod.update()
        probs = None
        if epoch >= args.burn_in:
            # this parameter snapshot IS a posterior sample
            probs = predict_probs(xte)
            posterior += probs
            sample_accs.append((probs.argmax(1) == yte).mean())
            n_samples += 1
        if (epoch + 1) % 5 == 0:
            if probs is None:
                probs = predict_probs(xte)
            acc = (probs.argmax(1) == yte).mean()
            logging.info("Epoch[%d] sample-accuracy=%.4f", epoch, acc)

    mean_sample = float(np.mean(sample_accs))
    bayes = ((posterior / n_samples).argmax(1) == yte).mean()
    logging.info("mean single-sample accuracy=%.4f  posterior-mean "
                 "accuracy=%.4f (%d samples)", mean_sample, bayes,
                 n_samples)
    # the Bayesian average must solve the task AND not lose to the
    # AVERAGE single sample (individual SGLD samples are noisy by
    # design — comparing against one would be a coin flip)
    assert bayes >= 0.80, bayes
    assert bayes >= mean_sample - 0.02, (bayes, mean_sample)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
