#!/usr/bin/env python
"""Train a fully-connected autoencoder on (synthetic) MNIST
(reference ``example/autoencoder``: stacked AE, here trained end-to-end
with the same 784-500-250-2-250-500-784 shape)::

    python examples/train_autoencoder.py --num-epochs 4
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402


def ae_symbol(dims=(784, 500, 250, 2)):
    """Encoder stack + mirrored decoder, L2 reconstruction loss
    (reference ``autoencoder.py`` make_encoder/make_decoder)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("target")
    x = data
    for i, d in enumerate(dims[1:]):
        x = mx.sym.FullyConnected(x, num_hidden=d, name="enc%d" % i)
        if i < len(dims) - 2:
            x = mx.sym.Activation(x, act_type="relu",
                                  name="enc%d_relu" % i)
    for i, d in enumerate(reversed(dims[:-1])):
        x = mx.sym.FullyConnected(x, num_hidden=d, name="dec%d" % i)
        if i < len(dims) - 2:
            x = mx.sym.Activation(x, act_type="relu",
                                  name="dec%d_relu" % i)
    return mx.sym.LinearRegressionOutput(x, label, name="recon")


def main():
    ap = argparse.ArgumentParser(description="Train an autoencoder")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)  # before the iterator: its shuffle draws from
    # the global numpy stream, so seeding after would leave run-to-run
    # nondeterminism in the epoch order
    it = mx.io.MNISTIter(batch_size=args.batch_size, flat=True,
                         num_examples=args.num_examples, seed=0)
    net = ae_symbol()
    mx.random.seed(0)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("target",), context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, 784))],
             label_shapes=[("target", (B, 784))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    mse = float("nan")
    for epoch in range(args.num_epochs):
        se = n = 0
        it.reset()
        for batch in it:
            x = batch.data[0]
            mod.forward_backward(DataBatch([x], [x]))
            mod.update()
            valid = x.shape[0] - batch.pad  # wrap-around padding rows
            rec = mod.get_outputs()[0].asnumpy()[:valid]
            se += float(((rec - x.asnumpy()[:valid]) ** 2).sum())
            n += rec.size
        mse = se / n
        logging.info("Epoch[%d] Train-MSE=%.5f", epoch, mse)
    print("final-mse=%.5f" % mse)
    return 0


if __name__ == "__main__":
    sys.exit(main())
