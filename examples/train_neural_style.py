#!/usr/bin/env python
"""Neural style transfer: optimize the INPUT image, not the weights::

    python examples/train_neural_style.py --steps 40

Port of the reference example family ``example/neural-style`` (content
+ style Gram losses on VGG features, total-variation smoothing,
gradient descent on the pixels).  This is the one driver whose
gradients flow to the DATA — ``x.attach_grad()`` + ``autograd.record``
+ ``backward()`` into the input buffer (``MXAutogradMarkVariables`` on
a non-parameter), a surface no weight-training example touches.

Differences from the reference kept deliberate: features come from a
randomly initialized ``gluon.model_zoo`` VGG-11 trunk (this build has
no pretrained weights and zero egress; random multi-scale conv
features still define a well-posed style/content objective — the
point here is the input-gradient machinery, and the loss must
demonstrably descend), images are small synthetic textures, and the
optimizer is plain adam on the pixel buffer.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon  # noqa: E402


def make_images(rng, size):
    """Synthetic 'photo' (smooth blobs) and 'style' (stripes)."""
    yy, xx = np.mgrid[0:size, 0:size] / size
    content = np.stack([
        np.exp(-((yy - 0.4) ** 2 + (xx - 0.5) ** 2) * 8),
        np.exp(-((yy - 0.7) ** 2 + (xx - 0.3) ** 2) * 12),
        yy * xx]).astype(np.float32)
    style = np.stack([
        np.sin(xx * 20), np.cos(yy * 16), np.sin((xx + yy) * 12)
    ]).astype(np.float32) * 0.5
    return content[None], style[None]


def gram(feat):
    b, c, h, w = feat.shape
    f = feat.reshape((c, h * w))
    return mx.nd.dot(f, f.T) * (1.0 / (c * h * w))


def main():
    ap = argparse.ArgumentParser(description="neural style transfer")
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--style-weight", type=float, default=50.0)
    ap.add_argument("--tv-weight", type=float, default=1e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    content_np, style_np = make_images(rng, args.size)

    from incubator_mxnet_tpu.gluon.model_zoo import vision

    trunk = vision.vgg11(classes=10).features
    trunk.initialize(mx.initializer.Xavier())
    # content from a deeper block, style Grams from several depths —
    # the classic multi-scale recipe (reference neural-style layer sets)
    style_layers, content_layer = (1, 4, 7), 9

    def extract(x):
        feats = {}
        for i, blk in enumerate(trunk._children):
            x = blk(x)
            if i in style_layers:
                feats[i] = x
            if i == content_layer:
                feats["content"] = x
                break
        return feats

    content = mx.nd.array(content_np)
    style = mx.nd.array(style_np)
    with autograd.pause():
        want_content = extract(content)["content"]
        want_grams = {i: gram(f) for i, f in extract(style).items()
                      if i != "content"}

    # the optimized variable IS the image — updated by THE library
    # adam (mx.optimizer), not a hand-rolled loop
    x = mx.nd.array(content_np + 0.1 * rng.randn(*content_np.shape)
                    .astype(np.float32))
    x.attach_grad()
    opt = mx.optimizer.create("adam", learning_rate=args.lr)
    opt_state = opt.create_state(0, x)
    first = last = None
    for step in range(1, args.steps + 1):
        with autograd.record():
            feats = extract(x)
            loss = mx.nd.sum(mx.nd.square(
                feats["content"] - want_content))
            for i in style_layers:
                loss = loss + args.style_weight * mx.nd.sum(
                    mx.nd.square(gram(feats[i]) - want_grams[i]))
            # total variation: neighbor differences on the pixels
            loss = loss + args.tv_weight * (
                mx.nd.sum(mx.nd.square(x[:, :, 1:, :] - x[:, :, :-1, :]))
                + mx.nd.sum(mx.nd.square(x[:, :, :, 1:]
                                         - x[:, :, :, :-1])))
        loss.backward()
        opt.update(0, x, x.grad, opt_state)
        last = float(loss.asnumpy())
        if first is None:
            first = last
        if step % 10 == 0 or step == 1:
            logging.info("Step[%d] style-loss=%.5f", step, last)
    # the input-gradient machinery must genuinely descend the
    # objective, not just wiggle it
    assert last < 0.5 * first, (first, last)
    logging.info("loss %.5f -> %.5f", first, last)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
