#!/usr/bin/env python
"""Train a DCGAN on synthetic images (reference ``example/gan/dcgan.py``)::

    python examples/train_dcgan.py --size 32 --num-epochs 2

The adversarial loop is the reference's exactly: the discriminator
module trains on real then fake batches, and the generator module
receives the discriminator's INPUT gradient through
``Module.backward(out_grads=...)`` — the external-gradient API.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402
from incubator_mxnet_tpu.models import dcgan  # noqa: E402


def real_batches(rng, n, batch, nc, size):
    """Synthetic 'real' data: smooth blobs in [-1, 1] (tanh range)."""
    for _ in range(n):
        base = rng.randn(batch, nc, 4, 4)
        img = np.repeat(np.repeat(base, size // 4, 2), size // 4, 3)
        yield np.tanh(img).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description="Train DCGAN")
    ap.add_argument("--size", type=int, default=32, choices=(32, 64))
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--z-dim", type=int, default=16)
    ap.add_argument("--ngf", type=int, default=16)
    ap.add_argument("--ndf", type=int, default=16)
    ap.add_argument("--nc", type=int, default=3)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    B, Z, nc, size = args.batch_size, args.z_dim, args.nc, args.size
    gen_sym, disc_sym = dcgan.make_dcgan_sym(ngf=args.ngf, ndf=args.ndf,
                                             nc=nc, size=size)

    mx.random.seed(0)
    gen = mx.mod.Module(gen_sym, data_names=("rand",), label_names=(),
                        context=mx.cpu())
    gen.bind(data_shapes=[("rand", (B, Z, 1, 1))])
    gen.init_params(mx.initializer.Normal(0.02))
    gen.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr,
                                         "beta1": 0.5})
    disc = mx.mod.Module(disc_sym, data_names=("data",),
                         label_names=("label",), context=mx.cpu())
    disc.bind(data_shapes=[("data", (B, nc, size, size))],
              label_shapes=[("label", (B, 1))],
              inputs_need_grad=True)
    disc.init_params(mx.initializer.Normal(0.02))
    disc.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    rng = np.random.RandomState(0)
    ones = mx.nd.array(np.ones((B, 1), np.float32))
    zeros = mx.nd.array(np.zeros((B, 1), np.float32))

    for epoch in range(args.num_epochs):
        dls, gls = [], []
        for real in real_batches(rng, args.num_batches, B, nc, size):
            noise = rng.randn(B, Z, 1, 1).astype(np.float32)
            gen.forward(DataBatch([mx.nd.array(noise)], []),
                        is_train=True)
            fake = gen.get_outputs()[0]

            # --- discriminator: fake batch (label 0) ------------------
            disc.forward(DataBatch([fake.copy()], [zeros]),
                         is_train=True)
            disc.backward()
            grads_fake = [
                [g.copy() for g in glist]
                for glist in disc._exec_group.grad_arrays]
            # --- discriminator: real batch (label 1) ------------------
            disc.forward(DataBatch([mx.nd.array(real)], [ones]),
                         is_train=True)
            disc.backward()
            # accumulate fake-pass grads (reference gradmod pattern)
            for glist, flist in zip(disc._exec_group.grad_arrays,
                                    grads_fake):
                for g, f in zip(glist, flist):
                    g += f
            disc.update()
            dls.append(float(disc.get_outputs()[0].asnumpy().mean()))

            # --- generator: fool the discriminator (label 1) ----------
            disc.forward(DataBatch([fake], [ones]), is_train=True)
            disc.backward()
            diff = disc.get_input_grads()[0]
            gen.backward([diff])          # external out_grads
            gen.update()
            gls.append(float(disc.get_outputs()[0].asnumpy().mean()))
        logging.info("Epoch[%d] D(real-pass out)=%.3f D(G(z))=%.3f",
                     epoch, np.mean(dls), np.mean(gls))
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
