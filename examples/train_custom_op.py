#!/usr/bin/env python
"""Train through a python CustomOp (numpy-ops example family)::

    python examples/train_custom_op.py --num-epochs 20

Port of the reference ``example/numpy-ops``: the network's loss layer
is a USER-DEFINED python operator — ``NumpySoftmax`` implements the
softmax + cross-entropy gradient with plain numpy inside
``CustomOp.forward``/``backward`` — registered via
``mx.operator.register`` and instantiated in-graph with
``mx.sym.Custom(op_type=...)``.  The driver proves the custom-operator
callback machinery end to end in a REAL training loop (Module fit
semantics, MNIST-shaped synthetic task), not just the op unit tests.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
import incubator_mxnet_tpu.operator as mxop  # noqa: E402


@mxop.register("numpy_softmax")
class NumpySoftmaxProp(mxop.CustomOpProp):
    """The reference example's NumpySoftmax: loss layer in pure numpy
    (softmax forward; softmax − onehot backward, SoftmaxOutput
    semantics with the label as the second input)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class NumpySoftmax(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = np.asarray(in_data[0])
                e = np.exp(x - x.max(axis=1, keepdims=True))
                self.assign(out_data[0], req[0],
                            e / e.sum(axis=1, keepdims=True))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                p = np.array(out_data[0])
                lab = np.asarray(in_data[1]).astype(int)
                p[np.arange(p.shape[0]), lab] -= 1.0
                self.assign(in_grad[0], req[0], p / p.shape[0])

        return NumpySoftmax()


def net(hidden, classes):
    x = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    x = mx.sym.FullyConnected(x, num_hidden=hidden, name="fc1")
    x = mx.sym.Activation(x, act_type="tanh", name="t1")
    x = mx.sym.FullyConnected(x, num_hidden=classes, name="fc2")
    return mx.sym.Custom(x, label, op_type="numpy_softmax",
                         name="softmax")


def main():
    ap = argparse.ArgumentParser(description="train via python CustomOp")
    ap.add_argument("--num-examples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1.0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.num_examples < args.batch_size:
        ap.error("--num-examples must be >= --batch-size")

    rng = np.random.RandomState(0)
    W = rng.randn(16, 10)
    X = rng.randn(args.num_examples, 16).astype(np.float32)
    y = np.argmax(X @ W + 0.3 * rng.randn(args.num_examples, 10),
                  1).astype(np.float32)

    mx.random.seed(0)
    mod = mx.mod.Module(net(32, 10), context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, 16))],
             label_shapes=[("softmax_label", (B,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    from incubator_mxnet_tpu.io import DataBatch

    nb = args.num_examples // B
    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(nb):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch([mx.nd.array(X[sl])],
                                           [mx.nd.array(y[sl])]))
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(1)
            correct += (pred == y[sl]).sum()
            total += pred.size
        acc = correct / total
        logging.info("Epoch[%d] Train-accuracy=%.4f", epoch, acc)
    assert acc > 0.9, acc
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
