#!/usr/bin/env python
"""Stochastic-depth training: randomly skip residual blocks per batch.

Reference family: ``example/stochastic-depth`` (``sd_module.py``,
``sd_mnist.py``): each residual block is a two-branch computation — an
identity skip plus a compute branch that a per-batch Bernoulli gate
turns OFF with probability ``death_rate`` during training (saving its
forward AND backward), while prediction adds the compute branch scaled
by the survival rate (the expectation).  The reference builds this as a
``BaseModule`` subclass composing two inner ``Module``s inside a
``SequentialModule`` chain; this driver exercises the same Module
container surface on the TPU-native stack — per-module executors, the
``auto_wiring`` output→data renaming, ``take_labels``, external
gradients through ``backward(out_grads)`` and ``get_input_grads``.

Zero-egress: trains on ``mx.io.MNISTIter``'s deterministic synthetic
digits, so accuracy is checkable.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx


class StochasticDepthModule(mx.mod.BaseModule):
    """Identity skip + randomly gated compute branch (per-batch gate).

    ``forward`` in train mode runs the compute branch only when the
    gate opens (probability ``1 - death_rate``); in test mode it always
    runs and its outputs are scaled by the survival rate.  ``backward``
    adds the compute branch's input grads only for an open gate —
    exactly the reference module's contract (``sd_module.py:136-170``).
    """

    def __init__(self, symbol_compute, data_names=("data",),
                 context=None, death_rate=0.0, seed=0):
        super(StochasticDepthModule, self).__init__(logger=logging)
        self._module = mx.mod.Module(symbol_compute,
                                     data_names=data_names,
                                     label_names=(),
                                     context=context or mx.cpu())
        self._open_rate = 1.0 - death_rate
        self._rng = np.random.RandomState(seed)
        self._gate_open = True
        self._outputs = None
        self._input_grads = None

    # ---- shape/name surface proxies the inner module -----------------
    @property
    def data_names(self):
        return self._module.data_names

    @property
    def output_names(self):
        return self._module.output_names

    @property
    def data_shapes(self):
        return self._module.data_shapes

    @property
    def label_shapes(self):
        return self._module.label_shapes

    @property
    def output_shapes(self):
        return self._module.output_shapes

    def get_params(self):
        return self._module.get_params()

    def init_params(self, *args, **kwargs):
        self._module.init_params(*args, **kwargs)
        self.params_initialized = True

    def bind(self, *args, **kwargs):
        # the compute branch must always expose input grads: the skip
        # path needs somewhere to add them
        kwargs = dict(kwargs)
        kwargs["inputs_need_grad"] = True
        self._module.bind(*args, **kwargs)
        self.binded = True
        self.for_training = self._module.for_training
        self.inputs_need_grad = self._module.inputs_need_grad

    def init_optimizer(self, *args, **kwargs):
        self._module.init_optimizer(*args, **kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self._module.for_training
        self._skip = [d.copy() for d in data_batch.data]
        if is_train:
            self._gate_open = self._rng.rand() < self._open_rate
            if self._gate_open:
                self._module.forward(data_batch, is_train=True)
                self._outputs = [
                    s + c for s, c in zip(self._skip,
                                          self._module.get_outputs())]
            else:
                self._outputs = self._skip
        else:
            self._module.forward(data_batch, is_train=False)
            self._outputs = [
                s + self._open_rate * c
                for s, c in zip(self._skip, self._module.get_outputs())]

    def backward(self, out_grads=None):
        # identity skip: its input grad IS the output grad
        self._input_grads = list(out_grads)
        if self._gate_open:
            self._module.backward(out_grads=out_grads)
            self._input_grads = [
                g + c for g, c in zip(self._input_grads,
                                      self._module.get_input_grads())]

    def update(self):
        if self._gate_open:
            self._module.update()

    def update_metric(self, eval_metric, labels):
        pass  # no loss head in a residual block

    def get_outputs(self, merge_multi_context=True):
        return self._outputs

    def get_input_grads(self, merge_multi_context=True):
        return self._input_grads

    def install_monitor(self, mon):
        self._module.install_monitor(mon)


def conv_bn_relu(name, data, num_filter, with_relu=True):
    conv = mx.sym.Convolution(data=data, num_filter=num_filter,
                              kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                              no_bias=True, name=name)
    bn = mx.sym.BatchNorm(data=conv, fix_gamma=False, momentum=0.9,
                          eps=2e-5, name=name + "_bn")
    return mx.sym.Activation(bn, act_type="relu") if with_relu else bn


def build_modules(num_blocks, num_filter, death_rate, ctx):
    """Stem module + ``num_blocks`` stochastic residual blocks + head."""
    seq = mx.mod.SequentialModule()
    stem = conv_bn_relu("stem", mx.sym.Variable("data"), num_filter)
    seq.add(mx.mod.Module(stem, label_names=(), context=ctx))
    for i in range(num_blocks):
        d = mx.sym.Variable("block%d_data" % i)
        branch = conv_bn_relu("block%d_a" % i, d, num_filter)
        branch = conv_bn_relu("block%d_b" % i, branch, num_filter,
                              with_relu=False)
        seq.add(StochasticDepthModule(branch,
                                      data_names=("block%d_data" % i,),
                                      context=ctx, death_rate=death_rate,
                                      seed=100 + i),
                auto_wiring=True)
    head_in = mx.sym.Variable("head_data")
    act = mx.sym.Activation(head_in, act_type="relu")
    pred = mx.sym.FullyConnected(mx.sym.Flatten(act), num_hidden=10)
    softmax = mx.sym.SoftmaxOutput(pred, name="softmax")
    seq.add(mx.mod.Module(softmax, data_names=("head_data",),
                          context=ctx),
            auto_wiring=True, take_labels=True)
    return seq


def main():
    p = argparse.ArgumentParser(
        description="stochastic-depth resnet (Module-composition family)")
    p.add_argument("--num-blocks", type=int, default=2)
    p.add_argument("--num-filter", type=int, default=8)
    p.add_argument("--death-rate", type=float, default=0.3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    mx.random.seed(0)
    train = mx.io.MNISTIter(image="absent-train-images",
                            label="absent-train-labels",
                            batch_size=args.batch_size, shuffle=True,
                            num_examples=args.num_examples, seed=0)
    seq = build_modules(args.num_blocks, args.num_filter,
                        args.death_rate, mx.cpu())
    seq.fit(train, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 8))
    # a second pass in PREDICTION mode (expectation path: every branch
    # scaled by the survival rate) must agree with what training reached
    logging.info("Predict-accuracy=%.4f", seq.score(train, "acc")[0][1])
    print("done")


if __name__ == "__main__":
    main()
