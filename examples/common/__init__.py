"""Shared example plumbing (reference ``example/image-classification/common``).

Importing this package makes ``incubator_mxnet_tpu`` importable when the
examples run from a source checkout (the ``find_mxnet.py`` role).
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

if os.environ.get("TP_EXAMPLES_FORCE_CPU") == "1":
    # the axon TPU plugin ignores JAX_PLATFORMS=cpu; tests force the CPU
    # backend via the config API before jax initializes (tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", "cpu")
    _n = int(os.environ.get("TP_EXAMPLES_CPU_DEVICES", "0"))
    if _n > 1:  # virtual device mesh for --pipeline / multi-device runs
        jax.config.update("jax_num_cpu_devices", _n)
