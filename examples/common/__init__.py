"""Shared example plumbing (reference ``example/image-classification/common``).

Importing this package makes ``incubator_mxnet_tpu`` importable when the
examples run from a source checkout (the ``find_mxnet.py`` role).
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

if os.environ.get("TP_EXAMPLES_FORCE_CPU", "0") == "1":
    # the axon TPU plugin ignores JAX_PLATFORMS=cpu; tests force the CPU
    # backend via the config API before jax initializes (tests/conftest.py)
    _n = int(os.environ.get("TP_EXAMPLES_CPU_DEVICES", "0"))
    if _n > 1 and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # portable spelling for jax < 0.5 (no jax_num_cpu_devices option);
        # must be set before the backend initializes
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=%d" % _n)

    import jax

    jax.config.update("jax_platforms", "cpu")
    if _n > 1:  # virtual device mesh for --pipeline / multi-device runs
        try:
            jax.config.update("jax_num_cpu_devices", _n)
        except AttributeError:
            pass  # older jax: XLA_FLAGS above already forced the mesh
