"""Shared training harness for the example drivers (reference
``example/image-classification/common/fit.py:1-200``): the ``--network
--batch-size --kv-store ...`` CLI and the kvstore-aware ``Module.fit``
wiring every BASELINE config runs through."""
from __future__ import annotations

import logging
import os
import time

import incubator_mxnet_tpu as mx


def add_fit_args(parser):
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str,
                       help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers, for networks such as resnet")
    train.add_argument("--gpus", type=str, default=None,
                       help="list of accelerator devices to run on, e.g. "
                            "'0' or '0,1' (mx.gpu aliases the TPU chip); "
                            "empty means cpu")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1,
                       help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, default=None,
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd",
                       help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9,
                       help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001,
                       help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128,
                       help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str, default=None,
                       help="model checkpoint prefix")
    train.add_argument("--monitor", type=int, default=0,
                       help="log network parameters every N iters if >0")
    train.add_argument("--load-epoch", type=int, default=None,
                       help="load the model saved at this epoch")
    train.add_argument("--top-k", type=int, default=0,
                       help="also report top-k accuracy (0 = off)")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--dtype", type=str, default="float32",
                       help="precision: float32 or bfloat16 (the "
                            "reference's float16 role)")
    return train


def _devices(args):
    if not getattr(args, "gpus", None):
        return mx.cpu() if mx.context.num_tpus() == 0 else mx.tpu(0)
    return [mx.gpu(int(i)) for i in args.gpus.split(",")]


def _get_lr_scheduler(args, kv, epoch_size):
    if args.lr_factor is None or args.lr_factor >= 1 \
            or not args.lr_step_epochs:
        return args.lr, None
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers
    epoch_size = max(1, epoch_size)
    begin_epoch = args.load_epoch or 0
    step_epochs = [int(e) for e in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d",
                     lr, begin_epoch)
    steps = [epoch_size * (e - begin_epoch) for e in step_epochs
             if e - begin_epoch > 0]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor)


def _load_model(args, rank=0):
    if args.load_epoch is None:
        return None, None, None
    assert args.model_prefix is not None
    prefix = args.model_prefix
    if rank > 0 and os.path.exists("%s-%d-symbol.json" % (prefix, rank)):
        prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", prefix, args.load_epoch)
    return sym, arg_params, aux_params


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    prefix = args.model_prefix if rank == 0 \
        else "%s-%d" % (args.model_prefix, rank)
    return mx.callback.do_checkpoint(prefix)


def fit(args, network, data_loader, **kwargs):
    """Train ``network`` with the iterators from ``data_loader(args, kv)``."""
    kv = mx.kv.create(args.kv_store)
    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.INFO, format=head, force=True)
    logging.info("start with arguments %s", args)

    train, val = data_loader(args, kv)

    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for d in batch.data:
                d.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size
                             / (time.time() - tic))
                tic = time.time()
        return None

    if "arg_params" in kwargs and "aux_params" in kwargs:
        arg_params = kwargs.pop("arg_params")
        aux_params = kwargs.pop("aux_params")
    else:
        sym, arg_params, aux_params = _load_model(args, kv.rank)
        if sym is not None:
            assert sym.tojson() == network.tojson()

    checkpoint = _save_model(args, kv.rank)
    devs = _devices(args)

    epoch_size = getattr(args, "num_examples", 0) // args.batch_size
    lr, lr_scheduler = _get_lr_scheduler(args, kv, epoch_size)

    optimizer_params = {"learning_rate": lr, "wd": args.wd,
                        "lr_scheduler": lr_scheduler}
    if args.optimizer in ("sgd", "nag", "dcasgd"):
        optimizer_params["momentum"] = args.mom

    if args.network == "alexnet":
        # AlexNet will not converge using Xavier (reference fit.py note)
        initializer = mx.init.Normal()
    else:
        initializer = mx.init.Xavier(rnd_type="gaussian",
                                     factor_type="in", magnitude=2)

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    extra_cb = kwargs.pop("batch_end_callback", None)
    if extra_cb is not None:
        batch_end_callbacks += extra_cb if isinstance(extra_cb, list) \
            else [extra_cb]
    monitor = mx.monitor.Monitor(args.monitor, pattern=".*") \
        if args.monitor > 0 else None

    model = mx.mod.Module(symbol=network, context=devs)
    model.fit(train,
              begin_epoch=args.load_epoch or 0,
              num_epoch=args.num_epochs,
              eval_data=val,
              eval_metric=kwargs.pop("eval_metric", eval_metrics),
              kvstore=kv,
              optimizer=args.optimizer,
              optimizer_params=optimizer_params,
              initializer=initializer,
              arg_params=arg_params,
              aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint,
              allow_missing=True,
              monitor=monitor,
              **kwargs)
    return model
