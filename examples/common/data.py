"""Example data pipelines (reference
``example/image-classification/common/data.py``): ImageRecordIter wiring
plus the ``--benchmark`` synthetic iterator the reference used for perf
runs (``train_imagenet.py --benchmark 1``)."""
from __future__ import annotations

import numpy as np

import incubator_mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, default=None,
                      help="the training data (RecordIO .rec)")
    data.add_argument("--data-val", type=str, default=None,
                      help="the validation data (RecordIO .rec)")
    data.add_argument("--image-shape", type=str, default="3,224,224",
                      help="the image shape feed into the network, e.g. "
                           "3,224,224")
    data.add_argument("--num-classes", type=int, default=1000,
                      help="the number of classes")
    data.add_argument("--num-examples", type=int, default=1281167,
                      help="the number of training examples")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939",
                      help="a tuple of size 3 for the mean rgb")
    data.add_argument("--preprocess-threads", type=int, default=4,
                      help="decode/augment thread-pool size")
    data.add_argument("--benchmark", type=int, default=0,
                      help="1 = use synthetic data to measure train speed")
    return data


def add_aug_args(parser):
    aug = parser.add_argument_group("Augmentation", "training augmentation")
    aug.add_argument("--random-crop", type=int, default=1,
                     help="if or not randomly crop the image")
    aug.add_argument("--random-mirror", type=int, default=1,
                     help="if or not randomly flip horizontally")
    return aug


class SyntheticImageIter(mx.io.DataIter):
    """Fixed random device-shaped batches — the ``--benchmark 1`` data
    path: measures the train step without any input pipeline."""

    def __init__(self, num_classes, data_shape, num_batches, dtype="float32"):
        super().__init__(data_shape[0])
        self.num_batches = num_batches
        rng = np.random.RandomState(0)
        self._data = mx.nd.array(
            rng.uniform(-1, 1, data_shape).astype(dtype))
        self._label = mx.nd.array(
            rng.randint(0, num_classes, (data_shape[0],)).astype("float32"))
        self.provide_data = [mx.io.DataDesc("data", data_shape, dtype)]
        self.provide_label = [mx.io.DataDesc(
            "softmax_label", (data_shape[0],), "float32")]
        self._cur = 0

    def reset(self):
        self._cur = 0

    def next(self):
        if self._cur >= self.num_batches:
            raise StopIteration
        self._cur += 1
        return mx.io.DataBatch(data=[self._data], label=[self._label],
                               pad=0, index=None,
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)

    __next__ = next


def get_image_iters(args, kv):
    """(train, val) iterators: RecordIO when ``--data-train`` is given,
    synthetic otherwise (so every driver runs out of the box)."""
    image_shape = tuple(int(d) for d in args.image_shape.split(","))
    batch_shape = (args.batch_size,) + image_shape
    if args.benchmark or not args.data_train:
        n_batches = max(1, args.num_examples // args.batch_size)
        train = SyntheticImageIter(args.num_classes, batch_shape, n_batches,
                                   args.dtype)
        return train, None

    mean = [float(v) for v in args.rgb_mean.split(",")]
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=image_shape,
        batch_size=args.batch_size, shuffle=True,
        rand_crop=bool(getattr(args, "random_crop", 1)),
        rand_mirror=bool(getattr(args, "random_mirror", 1)),
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        preprocess_threads=args.preprocess_threads)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=False,
            mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
            preprocess_threads=args.preprocess_threads)
    return train, val
