#!/usr/bin/env python
"""Dense-Sparse-Dense (DSD) training with a pruning SGD optimizer.

Reference family: ``example/dsd`` (``sparse_sgd.py``/``mlp.py``): a
user-registered ``SGD`` subclass prunes the smallest weights by
magnitude at scheduled epochs — ``mask = topk(|w|, ret_typ='mask')`` —
and thereafter multiplies weight, gradient, and momentum state by the
mask on every update, so training proceeds dense → sparse → dense
(sparsity back to 0) per the DSD paper's schedule.  This driver
exercises the optimizer-extension surface on the TPU-native stack: the
``@mx.optimizer.register`` decorator, ``create(name)`` lookup by
lowercased class name, ``param_idx2name`` plumbing from ``Module``, and
the ``topk``/``abs``/comparison NDArray ops the mask needs.

Zero-egress: trains an MLP on ``mx.io.MNISTIter``'s synthetic digits;
the run asserts the sparsity actually achieved during the sparse phase
and that accuracy recovers in the final dense phase.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx


@mx.optimizer.register
class SparseSGD(mx.optimizer.SGD):
    """SGD that masks pruned weights (DSD: arxiv 1607.04381).

    At the start of each scheduled phase the per-weight mask is
    recomputed from the CURRENT weight magnitudes (``topk`` mask of
    ``|w|``); until the next switch, every update multiplies weight,
    grad, and momentum state by that mask, so pruned coordinates stay
    exactly zero while the survivors keep training.
    """

    def __init__(self, pruning_switch_epoch=(1,), weight_sparsity=(0.0,),
                 bias_sparsity=(0.0,), batches_per_epoch=1, **kwargs):
        super(SparseSGD, self).__init__(**kwargs)
        self.phase_ends = [int(e) for e in pruning_switch_epoch]
        self.sparsity = [float(s) for s in weight_sparsity]
        self.bias_sparsity = [float(s) for s in bias_sparsity]
        self.batches_per_epoch = int(batches_per_epoch)
        self.masks = {}
        self.phase_of = {}  # index -> phase already masked for

    def _epoch(self, index):
        return self._index_update_count.get(index, 0) \
            // self.batches_per_epoch

    def _phase(self, epoch):
        for i, end in enumerate(self.phase_ends):
            if epoch < end:
                return i
        return len(self.phase_ends) - 1

    def update(self, index, weight, grad, state):
        # phase bookkeeping BEFORE the count bump: update 0 is epoch 0
        phase = self._phase(self._epoch(index))
        if self.phase_of.get(index) != phase:
            self.phase_of[index] = phase
            is_bias = self.idx2name.get(index, "").endswith("bias")
            sp = (self.bias_sparsity if is_bias
                  else self.sparsity)[phase]
            if sp <= 0.0:
                self.masks.pop(index, None)  # dense phase: no mask
            else:
                # threshold mask, not topk(ret_typ='mask'): the one-hot
                # mask expansion is O(k*n) memory (3 GB at the default
                # fc1 already); the kth |w| as a threshold is O(n)
                flat = mx.nd.abs(weight).reshape((weight.size,))
                keep = max(int(round(weight.size * (1.0 - sp))), 1)
                kth = mx.nd.topk(flat, k=keep, ret_typ="value")[keep - 1]
                self.masks[index] = (
                    mx.nd.abs(weight) >= kth).astype(np.float32)
                logging.info("Sparsity Update: %s -> %.0f%% pruned",
                             self.idx2name.get(index, index), sp * 100)
        mask = self.masks.get(index)
        if mask is not None:
            weight[:] = weight * mask
            grad[:] = grad * mask
            if state is not None and not isinstance(state, tuple):
                state[:] = state * mask
        super(SparseSGD, self).update(index, weight, grad, state)
        if mask is not None:  # keep pruned coords exactly zero
            weight[:] = weight * mask


def mlp_symbol(num_hidden):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=num_hidden // 2, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(out, name="softmax")


def weight_sparsity(mod):
    arg, _ = mod.get_params()
    zeros = sum(int((np.abs(v.asnumpy()) < 1e-12).sum())
                for n, v in arg.items() if n.endswith("weight"))
    total = sum(v.size for n, v in arg.items() if n.endswith("weight"))
    return zeros / float(total)


def main():
    p = argparse.ArgumentParser(
        description="DSD training (pruning SparseSGD optimizer family)")
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--epochs-per-phase", type=int, default=4)
    p.add_argument("--sparsity", type=float, default=0.7,
                   help="fraction pruned during the sparse phase")
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    if args.num_examples < args.batch_size:
        p.error("--num-examples must be >= --batch-size")
    mx.random.seed(0)
    E = args.epochs_per_phase
    batches = args.num_examples // args.batch_size
    train = mx.io.MNISTIter(image="absent-train-images",
                            label="absent-train-labels",
                            batch_size=args.batch_size, shuffle=True,
                            num_examples=args.num_examples, seed=0,
                            flat=True)
    mod = mx.mod.Module(mlp_symbol(args.num_hidden), context=mx.cpu())

    accs = {}

    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label, for_training=True)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.34))

    def run_phase(name, num_epoch, sparsity, lr, momentum):
        # each phase gets a FRESH SparseSGD (new masks, new schedule);
        # fit() sees the optimizer already initialized and keeps it.
        # The DSD paper lowers the learning rate entering the S and
        # re-D phases (momentum restarted at a converged point at the
        # dense-phase lr diverges); run_phase takes per-phase lr/mom.
        mod.init_optimizer(
            kvstore="local", optimizer="sparsesgd",
            optimizer_params={
                "learning_rate": lr, "momentum": momentum,
                "pruning_switch_epoch": (num_epoch,),
                "weight_sparsity": (sparsity,),
                "batches_per_epoch": batches},
            force_init=True)
        mod.fit(train, num_epoch=num_epoch, optimizer="sparsesgd",
                eval_metric="acc")
        accs[name] = mod.score(train, "acc")[0][1]
        sp = weight_sparsity(mod)
        logging.info("phase %s: accuracy=%.4f weight-sparsity=%.3f",
                     name, accs[name], sp)
        return sp

    # DSD schedule: dense -> sparse (prune) -> dense (masks lifted),
    # later phases at half lr without momentum (the paper's recipe)
    run_phase("dense1", E, 0.0, args.lr, 0.9)
    sp = run_phase("sparse", E, args.sparsity, args.lr / 2, 0.0)
    assert sp >= args.sparsity * 0.9, \
        "sparse phase pruned only %.3f" % sp
    run_phase("dense2", E, 0.0, args.lr / 2, 0.0)
    logging.info("DSD accuracies: %s",
                 {k: round(v, 4) for k, v in accs.items()})
    print("done")


if __name__ == "__main__":
    main()
