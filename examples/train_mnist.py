#!/usr/bin/env python
"""Train mnist (BASELINE config 1; reference
``example/image-classification/train_mnist.py``)::

    python examples/train_mnist.py --network lenet --num-epochs 2

Uses ``mx.io.MNISTIter`` — real ubyte files when present under
``--data-dir``, deterministic synthetic digits otherwise."""
import argparse
import logging

from common import fit  # noqa: F401  (sys.path bootstrap)

import incubator_mxnet_tpu as mx


def get_mnist_iter(args, kv):
    import os
    flat = args.network == "mlp"
    d = args.data_dir
    train = mx.io.MNISTIter(
        image=os.path.join(d, "train-images-idx3-ubyte"),
        label=os.path.join(d, "train-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=True,
        num_examples=args.num_examples, seed=0, flat=flat)
    val = mx.io.MNISTIter(
        image=os.path.join(d, "t10k-images-idx3-ubyte"),
        label=os.path.join(d, "t10k-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=False,
        num_examples=max(args.batch_size, args.num_examples // 6),
        seed=1, flat=flat)
    return train, val


def main():
    parser = argparse.ArgumentParser(
        description="train mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=60000)
    parser.add_argument("--data-dir", type=str, default="data",
                        help="directory holding the MNIST ubyte(.gz) "
                             "files; synthetic digits when absent")
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=10, lr=0.05,
                        lr_step_epochs="10", batch_size=64,
                        kv_store="local")
    args = parser.parse_args()

    if args.network == "mlp":
        sym = mx.models.mlp(num_classes=args.num_classes)
    else:
        sym = mx.models.lenet(num_classes=args.num_classes)
    fit.fit(args, sym, get_mnist_iter)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
