#!/usr/bin/env python
"""FGSM adversarial examples: perturb inputs along the loss gradient.

Reference family: ``example/adversary`` (``adversary_generation.ipynb``):
train an MNIST classifier, then compute the loss gradient WITH RESPECT
TO THE INPUT (``inputs_need_grad=True`` binding) and add
``epsilon * sign(grad)`` — the fast gradient sign method — to
demonstrate how sharply accuracy collapses under an imperceptible
perturbation.  Exercises the input-gradient surface of ``Module``
(``bind(inputs_need_grad=True)`` + ``get_input_grads``) on a trained
net, plus the ``sign`` op.

Zero-egress: uses ``mx.io.MNISTIter``'s synthetic digits; the driver
asserts clean accuracy is high and FGSM accuracy collapses.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx


def lenet_symbol():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=8,
                            name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=16,
                            name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    fc1 = mx.sym.FullyConnected(mx.sym.Flatten(p2), num_hidden=64,
                                name="fc1")
    a3 = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(a3, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def accuracy(mod, data, label):
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(data)]),
                is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
    return float((pred == label).mean())


def main():
    p = argparse.ArgumentParser(
        description="FGSM adversarial examples (adversary family)")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--epsilon", type=float, default=0.15)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    mx.random.seed(0)
    train = mx.io.MNISTIter(image="absent-train-images",
                            label="absent-train-labels",
                            batch_size=args.batch_size, shuffle=True,
                            num_examples=args.num_examples, seed=0)
    mod = mx.mod.Module(lenet_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": 0.9},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            eval_metric="acc")
    arg_params, aux_params = mod.get_params()

    # adversarial module: same net, inputs_need_grad=True so backward
    # leaves d(loss)/d(pixels) in get_input_grads()
    B = args.batch_size
    adv = mx.mod.Module(lenet_symbol(), context=mx.cpu())
    adv.bind(data_shapes=[("data", (B, 1, 28, 28))],
             label_shapes=[("softmax_label", (B,))],
             for_training=True, inputs_need_grad=True)
    adv.set_params(arg_params, aux_params)

    train.reset()
    batch = next(iter(train))
    x = batch.data[0].asnumpy()
    lab = batch.label[0].asnumpy().astype(np.int64)

    adv.forward(mx.io.DataBatch(data=[mx.nd.array(x)],
                                label=[mx.nd.array(lab)]),
                is_train=True)
    adv.backward()
    grad = adv.get_input_grads()[0]
    perturb = (args.epsilon * mx.nd.sign(grad)).asnumpy()
    x_adv = np.clip(x + perturb, 0.0, 1.0)

    clean = accuracy(adv, x, lab)
    fooled = accuracy(adv, x_adv, lab)
    logging.info("clean-accuracy=%.4f fgsm-accuracy=%.4f (eps=%.3f, "
                 "mean |perturb|=%.4f)", clean, fooled, args.epsilon,
                 float(np.abs(perturb).mean()))
    assert clean > 0.9, "classifier failed to train: %.4f" % clean
    assert fooled < clean - 0.3, \
        "FGSM barely moved accuracy: %.4f -> %.4f" % (clean, fooled)
    print("done")


if __name__ == "__main__":
    main()
