#!/usr/bin/env python
"""Train a PTB-style LSTM language model (BASELINE config 3; reference
``example/rnn/lstm_bucketing.py``)::

    python examples/train_ptb_lstm.py --num-epochs 5

Reads PTB text via ``--data-train ptb.train.txt`` (one sentence per line)
when given; otherwise generates a synthetic corpus so the driver runs
hermetically."""
import argparse
import logging

from common import fit  # noqa: F401  (sys.path bootstrap)

import numpy as np

import incubator_mxnet_tpu as mx

BUCKETS = [10, 20, 30, 40, 50, 60]


def tokenize_text(fname, vocab=None, invalid_label=0, start_label=1):
    with open(fname) as f:
        lines = [line.split() for line in f]
    return mx.rnn.encode_sentences(lines, vocab=vocab,
                                   invalid_label=invalid_label,
                                   start_label=start_label)


def synthetic_corpus(num_sentences, vocab_size, seed=0):
    rng = np.random.RandomState(seed)
    # first-order Markov chains so there is actual structure to learn
    trans = rng.dirichlet(np.ones(vocab_size) * 0.1, size=vocab_size)
    out = []
    for _ in range(num_sentences):
        w = int(rng.randint(1, vocab_size))
        s = [w]
        for _ in range(int(rng.randint(4, 30))):
            w = int(rng.choice(vocab_size, p=trans[w]))
            s.append(max(1, w))
        out.append(s)
    return out


def main():
    parser = argparse.ArgumentParser(
        description="train a PTB-style LSTM LM",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--vocab-size", type=int, default=200,
                        help="synthetic-corpus vocabulary size")
    parser.add_argument("--num-sentences", type=int, default=512,
                        help="synthetic-corpus size")
    parser.add_argument("--num-embed", type=int, default=200)
    parser.add_argument("--num-hidden", type=int, default=200)
    parser.add_argument("--num-lstm-layers", type=int, default=2)
    fit.add_fit_args(parser)
    parser.set_defaults(network="lstm", batch_size=32, num_epochs=25,
                        lr=0.01, optimizer="sgd", kv_store="local")
    args = parser.parse_args()
    kv = mx.kv.create(args.kv_store)
    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.INFO, format=head, force=True)
    logging.info("start with arguments %s", args)

    if args.data_train:
        sentences, vocab = tokenize_text(args.data_train)
        vocab_size = len(vocab) + 1
        val_sentences = None
        if args.data_val:
            val_sentences, _ = tokenize_text(args.data_val, vocab=vocab)
    else:
        vocab_size = args.vocab_size
        sentences = synthetic_corpus(args.num_sentences, vocab_size)
        val_sentences = synthetic_corpus(max(32, args.num_sentences // 8),
                                         vocab_size, seed=1)

    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=BUCKETS, invalid_label=0)
    val = mx.rnn.BucketSentenceIter(val_sentences, args.batch_size,
                                    buckets=BUCKETS, invalid_label=0) \
        if val_sentences else None

    from incubator_mxnet_tpu.models.lstm_ptb import lstm_ptb_sym_gen
    sym_gen = lstm_ptb_sym_gen(num_embed=args.num_embed,
                               num_hidden=args.num_hidden,
                               num_layers=args.num_lstm_layers,
                               vocab_size=vocab_size, fused=True)
    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train.default_bucket_key,
        context=fit._devices(args))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=kv, optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr, "wd": args.wd},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))
    return mod


if __name__ == "__main__":
    main()
