#!/usr/bin/env python
"""Train SSD object detection (BASELINE config 4; reference
``example/ssd/train.py``)::

    # full SSD-300 VGG16
    python examples/train_ssd.py --data-shape 300

    # fast smoke config (3 scales, 64x64)
    python examples/train_ssd.py --small-config --data-shape 64 \
        --num-epochs 2

Consumes an image list + directory (``--image-list``/``--data-root``,
the ``.lst`` convention of tools/im2rec.py); generates a small synthetic
detection set otherwise."""
import argparse
import logging
import os
import tempfile

from common import fit

import numpy as np

import incubator_mxnet_tpu as mx

SMALL_CFG = dict(
    from_layers=["relu4_3", "relu7", ""],
    num_filters=[512, -1, 256],
    strides=[-1, -1, 2],
    pads=[-1, -1, 1],
    sizes=[[0.2, 0.272], [0.45, 0.55], [0.8, 0.9]],
    ratios=[[1, 2, 0.5]] * 3,
    normalizations=[20, -1, -1],
    steps=[],
)


class MultiBoxMetric(mx.metric.EvalMetric):
    """Training loss over the Group([cls_prob, loc_loss, cls_label, det])
    outputs: class cross-entropy + smooth-l1 localization (the reference
    ``example/ssd/train/metric.py`` MultiBoxMetric)."""

    def __init__(self, eps=1e-8):
        super().__init__("multibox_loss")
        self.eps = eps

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()    # (B, C+1, N)
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()   # (B, N)
        valid = cls_label >= 0
        label = np.clip(cls_label.astype(np.int64), 0, None)
        prob = np.take_along_axis(cls_prob, label[:, None, :],
                                  axis=1).squeeze(1)
        ce = -np.log(np.maximum(prob, self.eps))[valid].sum()
        self.sum_metric += float(ce + loc_loss.sum())
        self.num_inst += max(int(valid.sum()), 1)


def synthetic_det_dataset(num_images, num_classes, seed=0):
    """Write random JPEGs + box labels, return (root, imglist)."""
    import cv2

    root = tempfile.mkdtemp(prefix="ssd_synth_")
    rng = np.random.RandomState(seed)
    imglist = []
    for i in range(num_images):
        img = rng.randint(0, 255, (160, 160, 3)).astype(np.uint8)
        name = "img_%d.jpg" % i
        cv2.imwrite(os.path.join(root, name), img)
        label = [2, 5]
        for _ in range(rng.randint(1, 4)):
            x1, y1 = rng.uniform(0, 0.5, 2)
            w, h = rng.uniform(0.2, 0.4, 2)
            label.extend([float(rng.randint(0, num_classes)), x1, y1,
                          min(x1 + w, 1.0), min(y1 + h, 1.0)])
        imglist.append([np.array(label, np.float32), name])
    return root, imglist


def read_lst(path):
    """tools/im2rec.py ``.lst`` rows: idx \t label... \t relpath"""
    imglist = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            label = np.array([float(v) for v in parts[1:-1]], np.float32)
            imglist.append([label, parts[-1]])
    return imglist


def main():
    parser = argparse.ArgumentParser(
        description="train an SSD detector",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--image-list", type=str, default=None,
                        help=".lst file (id, det label, path rows)")
    parser.add_argument("--data-root", type=str, default=None,
                        help="image directory the .lst paths are "
                             "relative to")
    parser.add_argument("--data-shape", type=int, default=300,
                        help="input image side")
    parser.add_argument("--num-classes", type=int, default=20)
    parser.add_argument("--num-examples", type=int, default=16,
                        help="synthetic dataset size when no --image-list")
    parser.add_argument("--small-config", action="store_true",
                        help="3-scale reduced SSD (fast smoke runs)")
    fit.add_fit_args(parser)
    parser.set_defaults(network="ssd", batch_size=4, num_epochs=240,
                        lr=0.004, wd=0.0005, kv_store="local")
    args = parser.parse_args()
    kv = mx.kv.create(args.kv_store)
    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.INFO, format=head, force=True)
    logging.info("start with arguments %s", args)

    if args.image_list:
        imglist = read_lst(args.image_list)
        root = args.data_root or os.path.dirname(args.image_list)
    else:
        root, imglist = synthetic_det_dataset(args.num_examples,
                                              args.num_classes)

    hw = args.data_shape
    it = mx.image.ImageDetIter(batch_size=args.batch_size,
                               data_shape=(3, hw, hw),
                               imglist=imglist, path_root=root,
                               shuffle=True, rand_mirror=True)

    if args.small_config:
        net = mx.models.ssd_train(num_classes=args.num_classes,
                                  **SMALL_CFG)
    else:
        net = mx.models.ssd_300(num_classes=args.num_classes, train=True)

    mod = mx.mod.Module(net, context=fit._devices(args),
                        data_names=("data",), label_names=("label",))
    optimizer_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag", "dcasgd"):  # fit.py:151 guard
        optimizer_params["momentum"] = args.mom
    mod.fit(it, num_epoch=args.num_epochs, kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(),
            eval_metric=MultiBoxMetric(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))
    return mod


if __name__ == "__main__":
    main()
