#!/usr/bin/env python
"""Deep Embedded Clustering (DEC): autoencoder + KL-refined clusters.

Reference family: ``example/dec`` (``dec.py``): pretrain an
autoencoder, k-means the latent space, then refine encoder AND cluster
centres by gradient descent on the DEC KL objective — implemented as a
user-defined python operator whose backward produces the paper's
closed-form gradients for both the embedding and the centres
(``dec.py:51-81``, a ``NumpyOp`` there; ``mx.operator.CustomOp`` here).
Exercises: CustomOp with THREE inputs and need_top_grad=False, a
Module-trained autoencoder whose encoder half is re-bound for feature
extraction, and executor-loop training where one argument (``dec_mu``)
is a non-layer parameter.

Zero-egress: clusters synthetic Gaussian blobs; cluster accuracy (best
label assignment) is asserted at the end.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.operator as mxop


@mxop.register("dec_loss")
class DECLossProp(mxop.CustomOpProp):
    """Student-t soft assignment q (forward) and the DEC paper's
    gradients wrt embedding z and centres mu (backward); the incoming
    target distribution p arrives as the ``label`` input, so no top
    gradient is needed."""

    def __init__(self, num_centers, alpha=1.0):
        super().__init__(need_top_grad=False)
        self.k = int(num_centers)
        self.alpha = float(alpha)

    def list_arguments(self):
        return ["data", "mu", "label"]

    def list_outputs(self):
        return ["q"]

    def infer_shape(self, in_shape):
        n, d = in_shape[0]
        return [in_shape[0], (self.k, d), (n, self.k)], [(n, self.k)], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        alpha, k = self.alpha, self.k

        class DECLoss(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                z = np.asarray(in_data[0])
                mu = np.asarray(in_data[1])
                d2 = ((z[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
                self._w = 1.0 / (1.0 + d2 / alpha)
                q = self._w ** ((alpha + 1.0) / 2.0)
                q /= q.sum(axis=1, keepdims=True)
                self.assign(out_data[0], req[0], q)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                z = np.asarray(in_data[0])
                mu = np.asarray(in_data[1])
                p = np.asarray(in_data[2])
                q = np.asarray(out_data[0])
                # dKL/dz_i = (a+1)/a * sum_j w_ij (p_ij - q_ij)(z_i - mu_j)
                w = (alpha + 1.0) / alpha * self._w * (p - q)
                dz = z * w.sum(axis=1, keepdims=True) - w.dot(mu)
                dmu = mu * w.sum(axis=0)[:, None] - w.T.dot(z)
                self.assign(in_grad[0], req[0], dz / z.shape[0])
                self.assign(in_grad[1], req[1], dmu / z.shape[0])

        return DECLoss()


def encoder_symbol(latent):
    x = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(x, num_hidden=32, name="enc1")
    h = mx.sym.Activation(h, act_type="relu")
    return mx.sym.FullyConnected(h, num_hidden=latent, name="enc2")


def autoencoder_symbol(latent, dim):
    z = encoder_symbol(latent)
    h = mx.sym.FullyConnected(z, num_hidden=32, name="dec1")
    h = mx.sym.Activation(h, act_type="relu")
    recon = mx.sym.FullyConnected(h, num_hidden=dim, name="dec2")
    return mx.sym.LinearRegressionOutput(recon,
                                         label=mx.sym.Variable("target"))


def kmeans(z, k, iters=50, seed=0):
    """Plain Lloyd's algorithm (the sklearn.KMeans role)."""
    rng = np.random.RandomState(seed)
    mu = z[rng.choice(len(z), k, replace=False)]
    for _ in range(iters):
        assign = ((z[:, None, :] - mu[None, :, :]) ** 2).sum(-1) \
            .argmin(axis=1)
        for j in range(k):
            if (assign == j).any():
                mu[j] = z[assign == j].mean(axis=0)
    return mu, assign


def cluster_acc(pred, truth):
    """Best one-to-one cluster→label assignment accuracy."""
    from scipy.optimize import linear_sum_assignment

    D = int(max(pred.max(), truth.max())) + 1
    w = np.zeros((D, D), np.int64)
    for i in range(pred.size):
        w[int(pred[i]), int(truth[i])] += 1
    rows, cols = linear_sum_assignment(w.max() - w)
    return w[rows, cols].sum() / float(pred.size)


def blobs(n, dim, k, spread=4.0, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, dim) * spread
    y = rng.randint(0, k, n)
    return (centers[y] + rng.randn(n, dim)).astype(np.float32), y


def main():
    p = argparse.ArgumentParser(
        description="deep embedded clustering (DEC family)")
    p.add_argument("--num-points", type=int, default=768)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--num-centers", type=int, default=4)
    p.add_argument("--latent", type=int, default=4)
    p.add_argument("--ae-epochs", type=int, default=30)
    p.add_argument("--dec-steps", type=int, default=60)
    p.add_argument("--update-interval", type=int, default=20,
                   help="steps between target-distribution refreshes")
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    mx.random.seed(0)
    X, y = blobs(args.num_points, args.dim, args.num_centers)
    N, k = len(X), args.num_centers

    # ---- stage 1: autoencoder pretraining (recon MSE) ----------------
    ae = mx.mod.Module(autoencoder_symbol(args.latent, args.dim),
                       data_names=("data",), label_names=("target",),
                       context=mx.cpu())
    it = mx.io.NDArrayIter({"data": X}, {"target": X}, batch_size=128,
                           shuffle=True)
    ae.fit(it, num_epoch=args.ae_epochs, optimizer="adam",
           optimizer_params={"learning_rate": 0.01},
           initializer=mx.initializer.Xavier(factor_type="in",
                                             magnitude=2.34),
           eval_metric="mse")
    arg_params, _ = ae.get_params()

    # ---- stage 2: k-means in the latent space ------------------------
    feat_sym = encoder_symbol(args.latent)
    feat = mx.mod.Module(feat_sym, data_names=("data",), label_names=(),
                         context=mx.cpu())
    feat.bind(data_shapes=[("data", (N, args.dim))], for_training=False)
    feat.init_params(arg_params={n: v for n, v in arg_params.items()
                                 if n in feat_sym.list_arguments()},
                     allow_missing=False)
    feat.forward(mx.io.DataBatch(data=[mx.nd.array(X)]), is_train=False)
    z0 = feat.get_outputs()[0].asnumpy()
    mu0, assign0 = kmeans(z0, k)
    logging.info("kmeans cluster-accuracy=%.4f", cluster_acc(assign0, y))

    # ---- stage 3: DEC refinement (encoder + centres jointly) ---------
    dec_sym = mx.sym.Custom(data=encoder_symbol(args.latent),
                            mu=mx.sym.Variable("dec_mu"),
                            label=mx.sym.Variable("p"),
                            op_type="dec_loss", num_centers=k,
                            name="dec")
    inputs = {"data", "p"}
    grad_req = {n: ("null" if n in inputs else "write")
                for n in dec_sym.list_arguments()}
    exe = dec_sym.simple_bind(mx.cpu(), grad_req=grad_req,
                              data=(N, args.dim), p=(N, k))
    for n, arr in exe.arg_dict.items():
        if n in arg_params:
            arr[:] = arg_params[n].asnumpy()
    exe.arg_dict["dec_mu"][:] = mu0
    exe.arg_dict["data"][:] = X

    opt = mx.optimizer.create("sgd", learning_rate=args.lr,
                              momentum=0.9, rescale_grad=1.0)
    updater = mx.optimizer.get_updater(opt)
    trainables = [n for n in dec_sym.list_arguments()
                  if grad_req[n] == "write"]

    kls = []
    for step in range(args.dec_steps):
        if step % args.update_interval == 0:
            exe.forward(is_train=False)
            q = exe.outputs[0].asnumpy()
            # target distribution: sharpen q, normalize per cluster
            f = q.sum(axis=0)
            target = (q ** 2 / f)
            target /= target.sum(axis=1, keepdims=True)
            exe.arg_dict["p"][:] = target
            pred = q.argmax(axis=1)
            kls.append(float((target * np.log(
                target / (q + 1e-9) + 1e-9)).sum() / N))
            logging.info("step %d cluster-accuracy=%.4f kl=%.5f",
                         step, cluster_acc(pred, y), kls[-1])
        exe.forward(is_train=True)
        exe.backward()
        for i, n in enumerate(trainables):
            updater(i, exe.grad_dict[n], exe.arg_dict[n])

    exe.forward(is_train=False)
    pred = exe.outputs[0].asnumpy().argmax(axis=1)
    acc = cluster_acc(pred, y)
    logging.info("final cluster-accuracy=%.4f", acc)
    assert acc > 0.9, "DEC refinement degraded clustering: %.4f" % acc
    assert len(kls) < 2 or kls[-1] < kls[0], \
        "DEC objective did not descend: %s" % kls
    print("done")


if __name__ == "__main__":
    main()
