#!/usr/bin/env python
"""Multiclass SVM on MNIST-shaped data (``SVMOutput``)::

    python examples/train_svm_mnist.py --num-epochs 10

Port of the reference ``example/svm_mnist``: the classifier head is
``SVMOutput`` — multiclass hinge loss with margin/regularization
attrs, L2 (squared-hinge) or ``use_linear=True`` L1 gradients — in
place of softmax.  The only driver exercising the SVM loss family.

Synthetic MNIST-shaped task: 10 gaussian digit prototypes in 784-d
with noise; linearly separable enough that the hinge head must reach
>0.9 accuracy (asserted), like the reference example's MNIST run.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def net(hidden, classes, margin, use_linear):
    x = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    x = mx.sym.FullyConnected(x, num_hidden=hidden, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.FullyConnected(x, num_hidden=classes, name="scores")
    return mx.sym.SVMOutput(x, label, margin=margin,
                            use_linear=use_linear, name="svm")


def main():
    ap = argparse.ArgumentParser(description="multiclass SVM head")
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=None,
                    help="default 0.001 (L2 squared-hinge grads are "
                         "violation-scaled) or 0.02 with --use-linear")
    ap.add_argument("--margin", type=float, default=1.0)
    ap.add_argument("--use-linear", action="store_true",
                    help="L1-SVM gradient (reference use_linear attr)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.num_examples < args.batch_size:
        ap.error("--num-examples must be >= --batch-size")
    if args.lr is None:
        args.lr = 0.02 if args.use_linear else 0.001

    rng = np.random.RandomState(0)
    protos = rng.randn(10, 784).astype(np.float32)
    y = rng.randint(0, 10, args.num_examples).astype(np.float32)
    X = protos[y.astype(int)] + 2.0 * rng.randn(
        args.num_examples, 784).astype(np.float32)

    mx.random.seed(0)
    B = args.batch_size
    mod = mx.mod.Module(net(128, 10, args.margin, args.use_linear),
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, 784))],
             label_shapes=[("softmax_label", (B,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9, "wd": 1e-4})
    from incubator_mxnet_tpu.io import DataBatch

    nb = args.num_examples // B
    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(nb):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch([mx.nd.array(X[sl])],
                                           [mx.nd.array(y[sl])]))
            mod.update()
            scores = mod.get_outputs()[0].asnumpy()
            correct += (scores.argmax(1) == y[sl]).sum()
            total += scores.shape[0]
        acc = correct / total
        logging.info("Epoch[%d] Train-accuracy=%.4f", epoch, acc)
    assert acc > 0.9, acc
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
