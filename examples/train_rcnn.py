#!/usr/bin/env python
"""Train Faster R-CNN end-to-end on synthetic detection data
(reference ``example/rcnn/train_end2end.py``)::

    python examples/train_rcnn.py --num-epochs 1 --num-images 8

The driver feeds the four-input train net (data, im_info, gt_boxes, RPN
label/bbox targets) with a minimal anchor-target assigner — enough to
drive every loss head; real datasets plug in through the same arrays.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# importing the package applies the TP_EXAMPLES_FORCE_CPU device pin
# (common/__init__.py) before the framework initializes a backend
import common  # noqa: E402,F401

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.models import rcnn  # noqa: E402


def synthetic_batch(rng, size, num_classes, na, fs):
    """One image + gt boxes + dense RPN targets (uniform sampling —
    the reference's AnchorLoader role at smoke scale)."""
    fh = fw = size // fs
    data = rng.rand(1, 3, size, size).astype(np.float32)
    im_info = np.array([[size, size, 1.0]], np.float32)
    n_gt = rng.randint(1, 3)
    boxes = []
    for _ in range(n_gt):
        x1, y1 = rng.randint(0, size // 2, 2)
        w, h = rng.randint(size // 4, size // 2, 2)
        boxes.append([x1, y1, min(x1 + w, size - 1),
                      min(y1 + h, size - 1),
                      rng.randint(1, num_classes)])
    gt = np.full((1, 4, 5), -1, np.float32)
    gt[0, :n_gt] = boxes
    label = rng.choice([-1.0, 0.0, 1.0], (1, na * fh * fw),
                       p=[0.7, 0.2, 0.1]).astype(np.float32)
    bbox_t = rng.randn(1, 4 * na, fh, fw).astype(np.float32) * 0.1
    bbox_w = (rng.rand(1, 4 * na, fh, fw) > 0.9).astype(np.float32)
    return data, im_info, gt, label, bbox_t, bbox_w


def main():
    ap = argparse.ArgumentParser(description="Train Faster R-CNN")
    ap.add_argument("--num-classes", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--num-images", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.001)
    ap.add_argument("--batch-rois", type=int, default=32)
    ap.add_argument("--post-nms", type=int, default=32)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    na, fs = rcnn.NUM_ANCHORS, 16
    size = args.image_size
    fh = fw = size // fs
    net = rcnn.get_symbol_train(num_classes=args.num_classes,
                                batch_rois=args.batch_rois,
                                post_nms=args.post_nms, pre_nms=256)
    shapes = dict(data=(1, 3, size, size), im_info=(1, 3),
                  gt_boxes=(1, 4, 5), label=(1, na * fh * fw),
                  bbox_target=(1, 4 * na, fh, fw),
                  bbox_weight=(1, 4 * na, fh, fw))
    ex = net.simple_bind(grad_req="write", **shapes)

    rng = np.random.RandomState(0)
    init = mx.initializer.Xavier()
    for n in ex.arg_dict:
        if n not in shapes:
            init(mx.init.InitDesc(n), ex.arg_dict[n])
    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              wd=5e-4)
    updater = mx.optimizer.get_updater(opt)

    for epoch in range(args.num_epochs):
        total = 0.0
        for it in range(args.num_images):
            batch = synthetic_batch(rng, size, args.num_classes, na, fs)
            for name, val in zip(["data", "im_info", "gt_boxes", "label",
                                  "bbox_target", "bbox_weight"], batch):
                ex.arg_dict[name][:] = mx.nd.array(val)
            ex.forward(is_train=True)
            ex.backward()
            for i, name in enumerate(net.list_arguments()):
                if name in shapes:
                    continue
                g = ex.grad_dict.get(name)
                if g is not None:
                    updater(i, g, ex.arg_dict[name])
            outs = [o.asnumpy() for o in ex.outputs]
            # rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss
            total += float(outs[1].sum() + outs[3].sum())
        logging.info("Epoch[%d] rcnn bbox-loss sum=%.4f", epoch, total)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
