#!/usr/bin/env python
"""Skip-gram word2vec with noise-contrastive estimation (NCE)::

    python examples/train_word2vec_nce.py --num-epochs 5

Port of the reference NCE example family (``example/nce-loss/nce.py``
+ ``wordvec.py``): the loss never materializes the full-vocab softmax —
each center word scores only its TRUE context word plus K noise words
sampled from the unigram^0.75 distribution, through a SHARED output
embedding (one ``Embedding`` lookup of the (B, 1+K) label matrix), a
broadcast inner product, and ``LogisticRegressionOutput`` against
{1, 0...} label weights.  Exercises the sampled/indexing surface at
scale: shared-weight Embedding, broadcast_mul, axis-sum, logistic
regression — the ops the softmax-based drivers never touch.

The synthetic corpus is Zipfian with a deterministic co-occurrence
rule (context of word w is w+1 mod V), so learning is verifiable: the
true context must out-score random words (`nce-accuracy` → 1).
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def nce_net(vocab, embed_dim):
    """Center-word embedding · shared-output-embedding NCE head."""
    data = mx.sym.Variable("data")                 # (B,) center ids
    label = mx.sym.Variable("label")               # (B, 1+K) true+noise
    label_weight = mx.sym.Variable("label_weight")  # (B, 1+K) {1,0}
    out_w = mx.sym.Variable("out_embed_weight")
    center = mx.sym.Embedding(data, input_dim=vocab,
                              output_dim=embed_dim, name="in_embed")
    cand = mx.sym.Embedding(label, input_dim=vocab,
                            output_dim=embed_dim, weight=out_w,
                            name="out_embed")
    pred = mx.sym.broadcast_mul(
        mx.sym.Reshape(center, shape=(-1, 1, embed_dim), name="ctr3d"),
        cand, name="scores3d")
    pred = mx.sym.sum(pred, axis=2, name="scores")
    return mx.sym.LogisticRegressionOutput(pred, label_weight,
                                           name="nce")


def make_batches(rng, vocab, batch, num_noise, n_batches):
    """Zipfian centers; true context = center+1 mod V; noise from the
    unigram^0.75 table (the word2vec negative-sampling distribution)."""
    zipf = 1.0 / np.arange(1, vocab + 1)
    unigram = zipf / zipf.sum()
    noise_p = unigram ** 0.75
    noise_p /= noise_p.sum()
    out = []
    for _ in range(n_batches):
        center = rng.choice(vocab, size=batch, p=unigram)
        true = (center + 1) % vocab
        noise = rng.choice(vocab, size=(batch, num_noise), p=noise_p)
        # a noise draw that hits the true context would carry a
        # contradictory 0-target (word2vec implementations exclude
        # the positive from its own negatives); nudge collisions
        hit = noise == true[:, None]
        noise = np.where(hit, (noise + 1) % vocab, noise)
        labels = np.concatenate([true[:, None], noise], axis=1)
        weights = np.zeros_like(labels, np.float32)
        weights[:, 0] = 1.0
        out.append((center.astype(np.float32),
                    labels.astype(np.float32), weights))
    return out


def main():
    ap = argparse.ArgumentParser(description="word2vec with NCE loss")
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--num-noise", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--num-batches", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--optimizer", default="adam")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    V, B, K = args.vocab_size, args.batch_size, args.num_noise
    net = nce_net(V, args.embed)
    rng = np.random.RandomState(0)
    batches = make_batches(rng, V, B, K, args.num_batches)

    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        data_names=("data",),
                        label_names=("label", "label_weight"))
    mod.bind(data_shapes=[("data", (B,))],
             label_shapes=[("label", (B, 1 + K)),
                           ("label_weight", (B, 1 + K))])
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer=args.optimizer,
                       optimizer_params={"learning_rate": args.lr})
    from incubator_mxnet_tpu.io import DataBatch

    for epoch in range(args.num_epochs):
        correct = total = 0
        for center, labels, weights in batches:
            batch = DataBatch([mx.nd.array(center)],
                              [mx.nd.array(labels),
                               mx.nd.array(weights)])
            mod.forward_backward(batch)
            mod.update()
            # NCE accuracy: the true context (col 0) out-scores every
            # sampled noise word for that center
            scores = mod.get_outputs()[0].asnumpy()
            correct += (scores[:, 0:1] > scores[:, 1:]).all(1).sum()
            total += scores.shape[0]
        logging.info("Epoch[%d] nce-accuracy=%.4f", epoch,
                     correct / total)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
