#!/usr/bin/env python
"""Framewise acoustic-model training: stacked BiLSTM on filterbanks.

Reference family: ``example/speech-demo`` / ``example/speech_recognition``
(minus the Kaldi/IO integration, which is external tooling): an
acoustic model consumes CONTINUOUS feature frames — log-filterbank
vectors, not token ids — through stacked (bidirectional) LSTMs and
predicts a phone state PER FRAME with a time-distributed softmax,
scored by frame accuracy.  Exercises the surface the token-based RNN
drivers don't: float sequence input straight into ``cell.unroll``
(no Embedding), a ``SequentialRNNCell`` stack of ``BidirectionalCell``
layers, and framewise labels.

Zero-egress: synthetic "speech" — each phone class is a fixed formant
template over the filterbank bins, an utterance is a random phone
sequence with each phone held for a random duration (HMM-style), plus
noise.  Frame accuracy is checkable and asserted.
"""
import argparse
import logging

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx


def synth_utterances(n, frames, bins, phones, seed=0):
    """(n, frames, bins) filterbanks + (n, frames) phone labels."""
    tmpl_rng = np.random.RandomState(42)
    templates = tmpl_rng.rand(phones, bins).astype(np.float32) * 2 - 1
    rng = np.random.RandomState(seed)
    feats = np.zeros((n, frames, bins), np.float32)
    labels = np.zeros((n, frames), np.float32)
    for i in range(n):
        t = 0
        while t < frames:
            ph = rng.randint(phones)
            dur = rng.randint(2, 6)           # each phone held 2-5 frames
            feats[i, t:t + dur] = templates[ph]
            labels[i, t:t + dur] = ph
            t += dur
    feats += rng.randn(*feats.shape).astype(np.float32) * 0.4
    return feats, labels


def acoustic_model(frames, bins, phones, hidden, layers):
    data = mx.sym.Variable("data")            # (B, frames, bins) floats
    label = mx.sym.Variable("softmax_label")  # (B, frames)
    stack = mx.rnn.SequentialRNNCell()
    for l in range(layers):
        stack.add(mx.rnn.BidirectionalCell(
            mx.rnn.LSTMCell(hidden, prefix="f%d_" % l),
            mx.rnn.LSTMCell(hidden, prefix="b%d_" % l),
            output_prefix="bi%d_" % l))
    outputs, _ = stack.unroll(frames, inputs=data, layout="NTC",
                              merge_outputs=True)
    flat = mx.sym.Reshape(outputs, shape=(-1, 2 * hidden))
    fc = mx.sym.FullyConnected(flat, num_hidden=phones, name="cls")
    lab = mx.sym.Reshape(label, shape=(-1,))
    sm = mx.sym.SoftmaxOutput(fc, lab, name="softmax")
    # fold the time axis back so predictions are (B, frames, phones)
    # against (B, frames) labels — the Accuracy metric argmaxes only
    # when the prediction carries an extra class axis (metric.py)
    return mx.sym.Reshape(sm, shape=(-1, frames, phones),
                          name="framewise")


def main():
    p = argparse.ArgumentParser(
        description="framewise BiLSTM acoustic model (speech family)")
    p.add_argument("--num-utts", type=int, default=256)
    p.add_argument("--frames", type=int, default=20)
    p.add_argument("--num-bins", type=int, default=24)
    p.add_argument("--num-phones", type=int, default=8)
    p.add_argument("--num-hidden", type=int, default=32)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    if args.num_utts < args.batch_size:
        p.error("--num-utts must be >= --batch-size")
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    mx.random.seed(0)
    X, Y = synth_utterances(args.num_utts, args.frames, args.num_bins,
                            args.num_phones)
    it = mx.io.NDArrayIter({"data": X}, {"softmax_label": Y},
                           batch_size=args.batch_size, shuffle=True)
    mod = mx.mod.Module(
        acoustic_model(args.frames, args.num_bins, args.num_phones,
                       args.num_hidden, args.num_layers),
        context=mx.cpu())
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            eval_metric=mx.metric.Accuracy(axis=-1))

    # framewise accuracy on the training distribution, predict mode:
    # (B, T, C) scores argmax over the trailing class axis against
    # (B, T) labels (reference metric.py:391 ndim semantics)
    acc = mod.score(it, mx.metric.Accuracy(axis=-1))[0][1]
    logging.info("frame-accuracy=%.4f", acc)
    assert acc > 0.85, "acoustic model under-trained: %.4f" % acc
    print("done")


if __name__ == "__main__":
    main()
