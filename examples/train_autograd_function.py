#!/usr/bin/env python
"""Imperative training with a custom ``autograd.Function``::

    python examples/train_autograd_function.py --num-epochs 15

Reference analog: ``python/mxnet/autograd.py:291`` (``Function``) —
user-defined forward/backward spliced into the imperative tape.  The
hidden activation here is a BinaryNet-style sign with a
straight-through estimator: the true derivative is zero almost
everywhere, so ordinary autograd cannot train through it; the custom
``backward`` passes the clipped cotangent instead.  The loop is fully
imperative (``attach_grad`` + ``record`` + ``backward`` + manual SGD)
— no Module, no Symbol.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd as ag  # noqa: E402


class binary_act(ag.Function):
    """sign(x) forward; straight-through backward, gated to |x| <= 1
    (the BinaryNet hard-tanh window)."""

    def forward(self, x):
        self.save_for_backward(x)
        return mx.nd.sign(x)

    def backward(self, dy):
        x, = self.saved_tensors
        gate = mx.nd.array(
            (np.abs(x.asnumpy()) <= 1.0).astype(np.float32))
        return dy * gate


def _softmax_xent(logits, labels_onehot):
    z = logits - mx.nd.max(logits, axis=1, keepdims=True)
    lse = mx.nd.log(mx.nd.sum(mx.nd.exp(z), axis=1, keepdims=True))
    return -mx.nd.sum(labels_onehot * (z - lse)) / logits.shape[0]


def main():
    ap = argparse.ArgumentParser(
        description="imperative straight-through training")
    ap.add_argument("--num-examples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--num-hidden", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.num_examples < args.batch_size:
        ap.error("--num-examples must be >= --batch-size")

    rng = np.random.RandomState(0)
    classes, feat = 4, 16
    W = rng.randn(feat, classes)
    X = rng.randn(args.num_examples, feat).astype(np.float32)
    y = np.argmax(X @ W, 1)
    onehot = np.eye(classes, dtype=np.float32)[y]

    params = [
        mx.nd.array(rng.randn(feat, args.num_hidden)
                    .astype(np.float32) * 0.3),
        mx.nd.array(np.zeros((1, args.num_hidden), np.float32)),
        mx.nd.array(rng.randn(args.num_hidden, classes)
                    .astype(np.float32) * 0.3),
        mx.nd.array(np.zeros((1, classes), np.float32)),
    ]
    for p in params:
        p.attach_grad()

    def net(xb):
        h = binary_act()(mx.nd.dot(xb, params[0]) + params[1])
        return mx.nd.dot(h, params[2]) + params[3]

    B = args.batch_size
    nb = args.num_examples // B
    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(nb):
            sl = slice(b * B, (b + 1) * B)
            xb = mx.nd.array(X[sl])
            with ag.record():
                logits = net(xb)
                loss = _softmax_xent(logits, mx.nd.array(onehot[sl]))
            loss.backward()
            for p in params:  # plain SGD on the accumulated grads
                p._set_data(p.data - args.lr * p.grad.data)
            pred = logits.asnumpy().argmax(1)
            correct += (pred == y[sl]).sum()
            total += pred.size
        acc = correct / total
        logging.info("Epoch[%d] Train-accuracy=%.4f", epoch, acc)
    assert acc > 0.7, acc
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
