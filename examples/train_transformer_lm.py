#!/usr/bin/env python
"""Train a decoder-only transformer LM on synthetic token data::

    python examples/train_transformer_lm.py --seq-len 64 --num-epochs 3

The task is next-token = (token + shift) mod vocab — learnable to 100%
accuracy, so the driver doubles as a correctness check.  Long-context
notes: on TPU the attention op routes to the Pallas flash kernel for
lane-aligned shapes, and sequences beyond one chip shard over an ``sp``
mesh axis (`docs/long_context.md`).
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description="Train a transformer LM")
    ap.add_argument("--vocab-size", type=int, default=64)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--shift", type=int, default=1)
    ap.add_argument("--fused-head", action="store_true",
                    help="chunked softmax-xent head: the (B*S, V) "
                         "logits never materialize — required for "
                         "large-vocab training (PERF.md §12); trains "
                         "through FusedTrainStep and reports loss "
                         "instead of accuracy")
    ap.add_argument("--remat", default=None,
                    help="recompute policy: 'mirror' or an int K "
                         "(TP_REMAT_SEGMENTS parity)")
    ap.add_argument("--grad-accum", type=int, default=None)
    ap.add_argument("--pipeline", type=int, default=0, metavar="L",
                    help="pipeline-parallel training over an L-stage "
                         "'pp' mesh axis (SymbolPipelineTrainStep: the "
                         "symbol is auto-partitioned at single-tensor "
                         "boundaries); microbatches = L; implies the "
                         "fused head; excludes --remat/--grad-accum")
    ap.add_argument("--moe-experts", type=int, default=0, metavar="E",
                    help="replace every FFN with a top-2 gated mixture "
                         "of E experts (_contrib_MoEFFN); trains via "
                         "FusedTrainStep with expert weights sharded "
                         "P('ep') when the device count divides by E; "
                         "logs balance-aux/overflow per epoch")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.pipeline:
        if args.remat is not None or args.grad_accum is not None:
            ap.error("--pipeline does not compose with --remat/"
                     "--grad-accum (stages are per-tick checkpointed "
                     "and microbatch accumulation is the schedule "
                     "itself)")
        if args.batch_size % args.pipeline:
            ap.error("--batch-size must divide into --pipeline "
                     "microbatches")
        if args.moe_experts:
            ap.error("--pipeline with --moe-experts is not supported "
                     "(route MoE through FusedTrainStep on an ep mesh)")

    V, B, S = args.vocab_size, args.batch_size, args.seq_len
    moe = args.moe_experts
    # the symbol is batch-polymorphic (-1 reshapes): the same graph
    # serves full batches, grad-accum microbatches and pipeline stages
    net = mx.models.transformer_lm(
        vocab_size=V, embed=args.embed, heads=args.heads,
        num_layers=args.num_layers, seq_len=S, batch_size=B,
        moe_experts=moe,
        head="fused" if args.fused_head or args.pipeline or moe
        else "softmax")

    rng = np.random.RandomState(0)
    data = rng.randint(0, V, (args.num_batches, B, S)).astype(np.float32)
    labels = (data + args.shift) % V

    mx.random.seed(0)
    if moe:
        import jax

        from incubator_mxnet_tpu import parallel

        remat = args.remat
        if remat is not None and remat != "mirror":
            remat = int(remat)
        P = jax.sharding.PartitionSpec
        n_dev = len(jax.devices())
        if n_dev % moe == 0 and n_dev > 1:
            mesh = parallel.build_mesh({"dp": n_dev // moe, "ep": moe})
            part = {n: P("ep") for n in net.list_arguments()
                    if "_moe_w" in n}
            logging.info("expert-parallel mesh dp%d x ep%d",
                         n_dev // moe, moe)
        else:
            mesh, part = parallel.default_mesh(1), None
        step = parallel.FusedTrainStep(
            net, {"data": (B, S)}, {"softmax_label": (B, S)},
            mesh=mesh, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier(), param_partition=part,
            remat=remat, grad_accum=args.grad_accum)
        for epoch in range(args.num_epochs):
            loss = aux = over = 0.0
            for b in range(args.num_batches):
                outs = step({"data": data[b],
                             "softmax_label": labels[b]})
                loss = float(np.asarray(outs[0]).mean())
                # under grad_accum the scalar stats stay stacked
                # per-microbatch — report the mean
                aux = float(np.asarray(outs[1]).mean())
                over = float(np.asarray(outs[2]).mean())
            logging.info("Epoch[%d] Train-loss=%.4f moe-aux=%.4f "
                         "moe-overflow=%.4f", epoch, loss, aux, over)
        print("done")
        return 0

    if args.pipeline:
        from incubator_mxnet_tpu import parallel
        from incubator_mxnet_tpu.parallel import SymbolPipelineTrainStep

        mesh = parallel.build_mesh({"pp": args.pipeline})
        step = SymbolPipelineTrainStep(
            net, {"data": (B, S)}, {"softmax_label": (B, S)},
            mesh=mesh, num_microbatches=args.pipeline,
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier())
        logging.info("pipeline stages (ops): %s",
                     [len(s) for s in step.stage_assignment])
        for epoch in range(args.num_epochs):
            loss = 0.0
            for b in range(args.num_batches):
                loss = step({"data": data[b],
                             "softmax_label": labels[b]}) / (B * S)
            logging.info("Epoch[%d] Train-loss=%.4f", epoch, loss)
        print("done")
        return 0
    if args.fused_head:
        # the flagship configuration (tools/bench_lm.py): one fused
        # fwd+bwd+adam program, optional remat / grad accumulation
        from incubator_mxnet_tpu import parallel

        remat = args.remat
        if remat is not None and remat != "mirror":
            remat = int(remat)
        step = parallel.FusedTrainStep(
            net, {"data": (B, S)}, {"softmax_label": (B, S)},
            mesh=parallel.default_mesh(1), optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier(), remat=remat,
            grad_accum=args.grad_accum)
        for epoch in range(args.num_epochs):
            loss = 0.0
            for b in range(args.num_batches):
                outs = step({"data": data[b],
                             "softmax_label": labels[b]})
                loss = float(np.asarray(outs[0]).mean())
            logging.info("Epoch[%d] Train-loss=%.4f", epoch, loss)
        print("done")
        return 0

    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, S))],
             label_shapes=[("softmax_label", (B, S))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    from incubator_mxnet_tpu.io import DataBatch

    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(args.num_batches):
            batch = DataBatch([mx.nd.array(data[b])],
                              [mx.nd.array(labels[b])])
            mod.forward_backward(batch)
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(-1)
            correct += (pred == labels[b].reshape(-1)).sum()
            total += pred.size
        logging.info("Epoch[%d] Train-accuracy=%.4f", epoch,
                     correct / total)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
