#!/usr/bin/env python
"""Multi-task training: one trunk, two heads (reference
``example/multi-task``: MNIST digit + synthetic parity label)::

    python examples/train_multi_task.py --num-epochs 3

Exercises the multi-output Module path: ``sym.Group`` of two
``SoftmaxOutput`` heads, two labels, and a per-head metric.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402


def multitask_symbol(num_digits=10):
    data = mx.sym.Variable("data")
    d_label = mx.sym.Variable("digit_label")
    p_label = mx.sym.Variable("parity_label")
    x = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="relu1")
    trunk = mx.sym.FullyConnected(x, num_hidden=64, name="fc2")
    trunk = mx.sym.Activation(trunk, act_type="relu", name="relu2")
    digit = mx.sym.FullyConnected(trunk, num_hidden=num_digits,
                                  name="digit_fc")
    digit = mx.sym.SoftmaxOutput(digit, d_label, name="digit")
    parity = mx.sym.FullyConnected(trunk, num_hidden=2, name="parity_fc")
    parity = mx.sym.SoftmaxOutput(parity, p_label, name="parity")
    return mx.sym.Group([digit, parity])


def main():
    ap = argparse.ArgumentParser(description="Multi-task training")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)  # before the iterator: its shuffle draws from
    # the global numpy stream, so seeding after would leave run-to-run
    # nondeterminism in the epoch order
    it = mx.io.MNISTIter(batch_size=args.batch_size, flat=True,
                         num_examples=args.num_examples, seed=0)
    net = multitask_symbol()
    mx.random.seed(0)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("digit_label", "parity_label"),
                        context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, 784))],
             label_shapes=[("digit_label", (B,)),
                           ("parity_label", (B,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})

    acc_d = acc_p = 0.0
    for epoch in range(args.num_epochs):
        cd = cp = n = 0
        it.reset()
        for batch in it:
            digits = batch.label[0].asnumpy()
            parity = (digits % 2).astype(np.float32)
            mod.forward_backward(DataBatch(
                batch.data, [batch.label[0], mx.nd.array(parity)]))
            mod.update()
            outs = [o.asnumpy() for o in mod.get_outputs()]
            valid = len(digits) - batch.pad  # wrap-around padding rows
            cd += (outs[0].argmax(1) == digits)[:valid].sum()
            cp += (outs[1].argmax(1) == parity)[:valid].sum()
            n += valid
        acc_d, acc_p = cd / n, cp / n
        logging.info("Epoch[%d] digit-acc=%.3f parity-acc=%.3f",
                     epoch, acc_d, acc_p)
    print("digit-acc=%.3f parity-acc=%.3f" % (acc_d, acc_p))
    return 0


if __name__ == "__main__":
    sys.exit(main())
