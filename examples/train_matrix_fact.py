#!/usr/bin/env python
"""Matrix-factorization recommender (reference ``example/recommenders``
demo1-MF: user/item embeddings, dot-product score, L2 loss)::

    python examples/train_matrix_fact.py --num-epochs 8

Synthetic ratings come from a planted low-rank model, so train RMSE
must drop well below the rating scale — the driver doubles as a
correctness check.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.io import DataBatch  # noqa: E402


def mf_symbol(num_users, num_items, factor=16):
    """score(u, i) = <user_emb[u], item_emb[i]> (reference plain_net)."""
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    u = mx.sym.Embedding(user, input_dim=num_users, output_dim=factor,
                         name="user_embed")
    v = mx.sym.Embedding(item, input_dim=num_items, output_dim=factor,
                         name="item_embed")
    pred = mx.sym.sum(u * v, axis=1, name="dot")
    return mx.sym.LinearRegressionOutput(pred, score, name="lro")


def main():
    ap = argparse.ArgumentParser(description="Train MF recommender")
    ap.add_argument("--num-users", type=int, default=64)
    ap.add_argument("--num-items", type=int, default=48)
    ap.add_argument("--factor", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--num-ratings", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    # planted low-rank ground truth
    gt_u = rng.randn(args.num_users, args.factor) * 0.5
    gt_v = rng.randn(args.num_items, args.factor) * 0.5
    users = rng.randint(0, args.num_users, args.num_ratings)
    items = rng.randint(0, args.num_items, args.num_ratings)
    scores = (np.einsum("nf,nf->n", gt_u[users], gt_v[items])
              + rng.randn(args.num_ratings) * 0.05).astype(np.float32)

    net = mf_symbol(args.num_users, args.num_items, args.factor)
    mx.random.seed(1)
    mod = mx.mod.Module(net, data_names=("user", "item"),
                        label_names=("score",), context=mx.cpu())
    B = args.batch_size
    mod.bind(data_shapes=[("user", (B,)), ("item", (B,))],
             label_shapes=[("score", (B,))])
    mod.init_params(mx.initializer.Normal(0.3))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    n_batches = args.num_ratings // B
    if n_batches == 0:
        ap.error("--num-ratings (%d) must be >= --batch-size (%d)"
                 % (args.num_ratings, B))
    rmse = float("nan")
    for epoch in range(args.num_epochs):
        se = 0.0
        order = rng.permutation(args.num_ratings)[:n_batches * B]
        for b in range(n_batches):
            sel = order[b * B:(b + 1) * B]
            batch = DataBatch(
                [mx.nd.array(users[sel].astype(np.float32)),
                 mx.nd.array(items[sel].astype(np.float32))],
                [mx.nd.array(scores[sel])])
            mod.forward_backward(batch)
            mod.update()
            pred = mod.get_outputs()[0].asnumpy()
            se += float(((pred - scores[sel]) ** 2).sum())
        rmse = np.sqrt(se / (n_batches * B))
        logging.info("Epoch[%d] Train-RMSE=%.4f", epoch, rmse)
    print("final-rmse=%.4f" % rmse)
    return 0


if __name__ == "__main__":
    sys.exit(main())
