#!/usr/bin/env python
"""Train cifar10 (reference
``example/image-classification/train_cifar10.py``)::

    python examples/train_cifar10.py --network resnet --num-layers 20

Synthetic 32x32 data unless ``--data-train`` points at a RecordIO pack."""
import argparse
import logging

from common import data, fit

import incubator_mxnet_tpu as mx


def main():
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_aug_args(parser)
    parser.set_defaults(network="resnet", num_layers=20,
                        num_classes=10, num_examples=50000,
                        image_shape="3,32,32",
                        batch_size=128, num_epochs=300,
                        lr=0.05, lr_step_epochs="200,250")
    args = parser.parse_args()
    image_shape = tuple(int(d) for d in args.image_shape.split(","))
    sym = mx.models.resnet(num_layers=args.num_layers,
                           num_classes=args.num_classes,
                           image_shape=image_shape) \
        if args.network == "resnet" else \
        mx.models.get_symbol(args.network, num_classes=args.num_classes,
                             image_shape=image_shape)
    fit.fit(args, sym, data.get_image_iters)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
