#!/usr/bin/env python
"""Model-parallel LSTM language model: each layer on its own device.

Reference family: ``example/model-parallel-lstm`` (``lstm.py:65-68``
pins every time-step cell of layer *l* into ctx group ``layer%d`` and
binds with a group2ctx map, so a deep unrolled LSTM whose parameters
don't fit one device spreads layer-wise across several).  This driver
exercises the same capability on the TPU-native stack: the unrolled
symbol is built with ``mx.AttrScope(ctx_group=...)`` annotations and
bound through ``simple_bind(group2ctx=...)`` — the lowered XLA program
spans the group devices, with cross-device copies at layer boundaries
(``lowering.py:lower_symbol_grouped``, the graph_executor.cc:279-393
AssignContext analog).

Zero-egress: trains on a synthetic deterministic-chain corpus
(next token = (3*t + 1) mod V), so falling perplexity is checkable.
On the single-TPU session all groups map to the one chip (placement is
still exercised end-to-end); under ``TP_EXAMPLES_CPU_DEVICES=N`` the
layers genuinely land on N distinct devices.
"""
import argparse
import logging
import math

import numpy as np

import common  # noqa: F401  (path setup + TP_EXAMPLES_FORCE_CPU)
import incubator_mxnet_tpu as mx


def lstm_cell(num_hidden, indata, prev_c, prev_h, param, layeridx, seqidx):
    """One LSTM step sharing layer ``param`` across timesteps."""
    i2h = mx.sym.FullyConnected(data=indata, weight=param["i2h_weight"],
                                bias=param["i2h_bias"],
                                num_hidden=num_hidden * 4,
                                name="l%d_t%d_i2h" % (layeridx, seqidx))
    h2h = mx.sym.FullyConnected(data=prev_h, weight=param["h2h_weight"],
                                bias=param["h2h_bias"],
                                num_hidden=num_hidden * 4,
                                name="l%d_t%d_h2h" % (layeridx, seqidx))
    gates = mx.sym.SliceChannel(i2h + h2h, num_outputs=4, axis=1,
                                name="l%d_t%d_gates" % (layeridx, seqidx))
    in_gate = mx.sym.Activation(gates[0], act_type="sigmoid")
    in_trans = mx.sym.Activation(gates[1], act_type="tanh")
    forget = mx.sym.Activation(gates[2], act_type="sigmoid")
    out_gate = mx.sym.Activation(gates[3], act_type="sigmoid")
    next_c = forget * prev_c + in_gate * in_trans
    next_h = out_gate * mx.sym.Activation(next_c, act_type="tanh")
    return next_c, next_h


def build_unrolled(num_layers, seq_len, vocab, num_embed, num_hidden):
    """Unrolled LSTM LM with layer-wise ctx groups.

    Layer *l*'s cells and parameters all carry ``ctx_group='layer<l>'``;
    the embedding rides with layer 0 and the decoder with the last
    layer (the reference's placement, ``lstm.py:151-163``).
    """
    data = mx.sym.Variable("data")          # (batch, seq_len) int ids
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="layer0"):
        embed_weight = mx.sym.Variable("embed_weight")
        embed = mx.sym.Embedding(data=data, weight=embed_weight,
                                 input_dim=vocab, output_dim=num_embed,
                                 name="embed")
        steps = mx.sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                    squeeze_axis=1, name="step_slices")

    params, states = [], []
    for l in range(num_layers):
        with mx.AttrScope(ctx_group="layer%d" % l):
            params.append({
                k: mx.sym.Variable("l%d_%s" % (l, k))
                for k in ("i2h_weight", "i2h_bias",
                          "h2h_weight", "h2h_bias")})
            states.append((mx.sym.Variable("l%d_init_c" % l),
                           mx.sym.Variable("l%d_init_h" % l)))

    hidden_all = []
    for t in range(seq_len):
        hidden = steps[t]
        for l in range(num_layers):
            with mx.AttrScope(ctx_group="layer%d" % l):
                c, h = lstm_cell(num_hidden, hidden, states[l][0],
                                 states[l][1], params[l], l, t)
            states[l] = (c, h)
            hidden = h
        hidden_all.append(hidden)

    with mx.AttrScope(ctx_group="layer%d" % (num_layers - 1)):
        concat = mx.sym.Concat(*hidden_all, dim=0, name="seq_concat")
        pred = mx.sym.FullyConnected(data=concat, num_hidden=vocab,
                                     name="decoder")
        # label arrives (batch, seq_len): to match the (seq major) concat
        # rows we transpose before flattening
        flat_label = mx.sym.Reshape(mx.sym.transpose(label, axes=(1, 0)),
                                    shape=(-1,))
        sm = mx.sym.SoftmaxOutput(data=pred, label=flat_label,
                                  name="softmax")
    return sm


def chain_corpus(num_batches, batch_size, seq_len, vocab, seed=0):
    """Deterministic-chain batches: t_{k+1} = (3 t_k + 1) mod vocab."""
    rng = np.random.RandomState(seed)
    for _ in range(num_batches):
        start = rng.randint(0, vocab, size=(batch_size, 1))
        seq = [start]
        for _ in range(seq_len):
            seq.append((3 * seq[-1] + 1) % vocab)
        seq = np.concatenate(seq, axis=1)
        yield seq[:, :seq_len].astype(np.float32), \
            seq[:, 1:seq_len + 1].astype(np.float32)


def main():
    p = argparse.ArgumentParser(
        description="model-parallel LSTM LM (layer-per-device group2ctx)")
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=32)
    p.add_argument("--vocab-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-batches", type=int, default=40)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--disp-batches", type=int, default=10)
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    import jax

    devices = [mx.Context("cpu" if d.platform == "cpu" else "gpu", i)
               for i, d in enumerate(jax.devices())]
    group2ctx = {"layer%d" % l: devices[l % len(devices)]
                 for l in range(args.num_layers)}
    logging.info("placement: %s",
                 {g: str(c) for g, c in group2ctx.items()})

    sym = build_unrolled(args.num_layers, args.seq_len, args.vocab_size,
                         args.num_embed, args.num_hidden)
    input_names = {"data", "softmax_label"}
    state_names = {n for n in sym.list_arguments() if "_init_" in n}
    grad_req = {n: ("null" if n in input_names or n in state_names
                    else "write") for n in sym.list_arguments()}
    exe = sym.simple_bind(
        devices[0], grad_req=grad_req, group2ctx=group2ctx,
        data=(args.batch_size, args.seq_len),
        softmax_label=(args.batch_size, args.seq_len),
        **{("l%d_init_%s" % (l, s)): (args.batch_size, args.num_hidden)
           for l in range(args.num_layers) for s in "ch"})

    init = mx.initializer.Xavier(factor_type="in", magnitude=2.34)
    for n, arr in exe.arg_dict.items():
        if grad_req[n] == "write":
            init(mx.initializer.InitDesc(n), arr)

    opt = mx.optimizer.create("sgd", learning_rate=args.lr, momentum=0.9,
                              wd=1e-5,
                              rescale_grad=1.0 / (args.batch_size *
                                                  args.seq_len))
    updater = mx.optimizer.get_updater(opt)
    trainables = [n for n in sym.list_arguments()
                  if grad_req[n] == "write"]

    for epoch in range(args.num_epochs):
        nll, count = 0.0, 0
        for i, (d, lbl) in enumerate(chain_corpus(
                args.num_batches, args.batch_size, args.seq_len,
                args.vocab_size, seed=epoch)):
            exe.arg_dict["data"][:] = d
            exe.arg_dict["softmax_label"][:] = lbl
            exe.forward(is_train=True)
            exe.backward()
            for k, n in enumerate(trainables):
                updater(k, exe.grad_dict[n], exe.arg_dict[n])
            prob = exe.outputs[0].asnumpy()  # (seq*batch, vocab) seq-major
            flat = lbl.T.reshape(-1).astype(np.int64)
            nll -= np.sum(np.log(np.maximum(
                prob[np.arange(flat.size), flat], 1e-10)))
            count += flat.size
            if (i + 1) % args.disp_batches == 0:
                logging.info("epoch %d batch %d perplexity=%.3f",
                             epoch, i + 1, math.exp(nll / count))
        logging.info("Epoch[%d] Train-perplexity=%.3f",
                     epoch, math.exp(nll / count))
    print("done")


if __name__ == "__main__":
    main()
