#!/usr/bin/env python
"""Train a tiny transformer LM, then SERVE it: continuous-batching
generation under concurrent clients with mixed prompt lengths::

    python examples/serve_transformer_lm.py --num-epochs 6 --clients 4

The task is next-token = (token + shift) mod vocab, so a trained model
makes generation verifiable: every generated token must continue the
shift chain.  Serving goes through ``mx.serving.GenerationEngine`` —
bucketed prefill + one compiled single-token decode step shared by all
in-flight sequences (finished requests free their cache slot and queued
prompts join the running batch without recompiling).  The engine's
compile bound is printed at the end: one program per (bucket, phase),
no matter how the client threads interleave.  See docs/serving.md.
"""
import argparse
import logging
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import common  # noqa: E402,F401  (TP_EXAMPLES_FORCE_CPU device pin)

import incubator_mxnet_tpu as mx  # noqa: E402


def train(args):
    """Fit the shift task with the Module path; returns arg_params."""
    V, B, S = args.vocab_size, args.batch_size, args.seq_len
    net = mx.models.transformer_lm(
        vocab_size=V, embed=args.embed, heads=args.heads,
        num_layers=args.num_layers, seq_len=S, batch_size=B,
        head="softmax")
    rng = np.random.RandomState(0)
    data = rng.randint(0, V, (args.num_batches, B, S)).astype(np.float32)
    labels = (data + args.shift) % V
    mx.random.seed(0)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, S))],
             label_shapes=[("softmax_label", (B, S))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    from incubator_mxnet_tpu.io import DataBatch

    acc = 0.0
    for epoch in range(args.num_epochs):
        correct = total = 0
        for b in range(args.num_batches):
            batch = DataBatch([mx.nd.array(data[b])],
                              [mx.nd.array(labels[b])])
            mod.forward_backward(batch)
            mod.update()
            pred = mod.get_outputs()[0].asnumpy().argmax(-1)
            correct += (pred == labels[b].reshape(-1)).sum()
            total += pred.size
        acc = correct / total
        logging.info("Epoch[%d] Train-accuracy=%.4f", epoch, acc)
    arg_params, _ = mod.get_params()
    return arg_params, acc


def main():
    ap = argparse.ArgumentParser(
        description="Serve a tiny transformer LM with continuous "
                    "batching")
    ap.add_argument("--vocab-size", type=int, default=32)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--shift", type=int, default=1)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests-per-client", type=int, default=3)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--new-tokens", type=int, default=6)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    V = args.vocab_size

    arg_params, acc = train(args)

    model = mx.serving.KVTransformerLM(arg_params, heads=args.heads)
    rng = np.random.RandomState(1)
    correct = [0]
    total = [0]
    lock = threading.Lock()
    errors = []

    def client(cid, eng):
        crng = np.random.RandomState(100 + cid)
        try:
            for _ in range(args.requests_per_client):
                plen = int(crng.randint(1, args.seq_len
                                        - args.new_tokens - 1))
                start = int(crng.randint(0, V))
                # a shift chain: the model should continue it
                prompt = (start + args.shift
                          * np.arange(plen)) % V
                res = eng.submit(prompt.astype(np.int32),
                                 max_new_tokens=args.new_tokens) \
                    .result(timeout=300)
                want = (prompt[-1] + args.shift
                        * np.arange(1, args.new_tokens + 1)) % V
                with lock:
                    correct[0] += int((res.tokens == want).sum())
                    total[0] += args.new_tokens
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    with mx.serving.GenerationEngine(model, max_slots=args.max_slots,
                                     max_len=args.seq_len) as eng:
        threads = [threading.Thread(target=client, args=(c, eng))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = model.stats
        logging.info("served %d requests, %d/%d generated tokens "
                     "continue the shift chain", stats.requests,
                     correct[0], total[0])
        logging.info("compiled programs: %d (%s)", stats.num_compiles,
                     sorted(k[0] for k in stats.compile_keys))
    if errors:
        raise errors[0]
    n_requests = args.clients * args.requests_per_client
    if stats.requests != n_requests:
        raise AssertionError("served %d of %d requests"
                             % (stats.requests, n_requests))
    # compile bound: exactly one decode program regardless of how many
    # sequences interleaved, and one prefill per (batch, length) bucket
    n_decode = sum(1 for k in stats.compile_keys if k[0] == "decode")
    n_prefill = sum(1 for k in stats.compile_keys if k[0] == "prefill")
    length_buckets = 1 + int(np.ceil(np.log2(args.seq_len)))
    batch_buckets = 1 + int(np.ceil(np.log2(args.max_slots)))
    if n_decode != 1 or n_prefill > length_buckets * batch_buckets:
        raise AssertionError("compile bound violated: %s"
                             % sorted(stats.compile_keys))
    if acc > 0.95 and correct[0] < total[0]:
        logging.warning("model at %.2f train accuracy missed %d tokens",
                        acc, total[0] - correct[0])
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
