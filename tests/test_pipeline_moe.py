"""Pipeline (pp) and expert (ep) parallelism — numeric contracts on the
virtual 8-device CPU mesh (SURVEY.md §4 philosophy: sharded result ==
single-device oracle)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.parallel import build_mesh
from incubator_mxnet_tpu.parallel.pipeline import pipeline_parallel_apply
from incubator_mxnet_tpu.parallel.moe import expert_parallel_moe, moe_ffn


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


@pytest.mark.parametrize("L,M", [(4, 8), (8, 8), (2, 3)])
def test_pipeline_matches_sequential(L, M):
    rng = np.random.RandomState(0)
    d = 16
    mesh = build_mesh({"pp": L})
    ws = jnp.asarray(rng.randn(L, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(L, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(M, 4, d).astype(np.float32))

    out = pipeline_parallel_apply(mesh, _stage_fn, (ws, bs), x)

    ref = np.asarray(x)
    for i in range(L):
        ref = np.tanh(ref @ np.asarray(ws[i]) + np.asarray(bs[i]))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_pipeline_differentiable():
    """Grads flow through the ppermute schedule (training path)."""
    rng = np.random.RandomState(1)
    L, M, d = 4, 4, 8
    mesh = build_mesh({"pp": L})
    ws = jnp.asarray(rng.randn(L, d, d).astype(np.float32) * 0.3)
    bs = jnp.zeros((L, d), jnp.float32)
    x = jnp.asarray(rng.randn(M, 2, d).astype(np.float32))

    def loss(ws, bs):
        return jnp.sum(pipeline_parallel_apply(mesh, _stage_fn,
                                               (ws, bs), x) ** 2)

    def loss_ref(ws, bs):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ ws[i] + bs[i])
        return jnp.sum(y ** 2)

    g = jax.grad(loss, argnums=(0, 1))(ws, bs)
    gr = jax.grad(loss_ref, argnums=(0, 1))(ws, bs)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def _moe_oracle(x, gate_w, w1s, w2s):
    """Dense single-device top-1 MoE reference."""
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expert = probs.argmax(-1)
    out = np.zeros_like(x)
    for t in range(x.shape[0]):
        e = expert[t]
        h = np.maximum(x[t] @ w1s[e], 0.0)
        out[t] = (h @ w2s[e]) * probs[t, e]
    return out


@pytest.mark.parametrize("E", [4, 8])
def test_moe_matches_dense(E):
    rng = np.random.RandomState(2)
    T, d, h = 8 * E, 16, 32  # T divisible by E (token sharding)
    mesh = build_mesh({"ep": E})
    x = rng.randn(T, d).astype(np.float32)
    gate_w = rng.randn(d, E).astype(np.float32)
    w1s = rng.randn(E, d, h).astype(np.float32) * 0.2
    w2s = rng.randn(E, h, d).astype(np.float32) * 0.2

    out = expert_parallel_moe(mesh, jnp.asarray(x), jnp.asarray(gate_w),
                              jnp.asarray(w1s), jnp.asarray(w2s))
    ref = _moe_oracle(x, gate_w, w1s, w2s)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_moe_composes_with_dp():
    """dp × ep on one mesh: batch shards over dp, experts over ep."""
    from incubator_mxnet_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    import functools

    rng = np.random.RandomState(3)
    E, T, d, h = 4, 16, 8, 16
    mesh = build_mesh({"dp": 2, "ep": E})
    P = jax.sharding.PartitionSpec
    x = rng.randn(2 * T, d).astype(np.float32)
    gate_w = rng.randn(d, E).astype(np.float32)
    w1s = rng.randn(E, d, h).astype(np.float32) * 0.2
    w2s = rng.randn(E, h, d).astype(np.float32) * 0.2

    def body(x, gw, w1, w2):
        return moe_ffn(x, gw, jnp.squeeze(w1, 0), jnp.squeeze(w2, 0),
                       "ep")

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
                   out_specs=P(("dp", "ep")))
    out = jax.jit(fn)(jnp.asarray(x), jnp.asarray(gate_w),
                      jnp.asarray(w1s), jnp.asarray(w2s))
    ref = _moe_oracle(x, gate_w, w1s, w2s)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# PipelineTrainStep: real pipelined training
# ---------------------------------------------------------------------------

def test_pipeline_train_step_matches_single_device():
    """A 4-stage pipelined LM (4 microbatches, fused head, adam) tracks
    the single-device FusedTrainStep loss curve on identical params and
    data — pipelined training is the same training."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel.pipeline import PipelineTrainStep

    V, E, H, L, S, B, M = 16, 16, 2, 4, 8, 8, 4
    rng = np.random.RandomState(0)

    net = mx.models.transformer_lm(vocab_size=V, embed=E, heads=H,
                                   num_layers=L, seq_len=S,
                                   batch_size=B, head="fused")
    mx.random.seed(11)
    fused = parallel.FusedTrainStep(
        net, {"data": (B, S)}, {"softmax_label": (B, S)},
        mesh=parallel.default_mesh(1), optimizer="adam",
        optimizer_params={"learning_rate": 3e-3},
        initializer=mx.initializer.Xavier())

    mesh = build_mesh({"pp": 4})
    pp = PipelineTrainStep(mesh, vocab_size=V, embed=E, heads=H,
                           num_layers=L, seq_len=S, batch_size=B,
                           num_microbatches=M, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
    # identical starting point: copy the fused step's params in
    arg_params, _ = fused.get_params()
    pp.set_params(arg_params)

    toks = rng.randint(0, V, (6, B, S)).astype(np.float32)
    labs = (toks + 1) % V
    for step_i in range(6):
        batch = {"data": toks[step_i], "softmax_label": labs[step_i]}
        outs = fused(batch)
        fused_loss = float(np.asarray(outs[0]).mean())
        pp_loss = pp(batch)
        np.testing.assert_allclose(pp_loss, fused_loss, rtol=2e-4,
                                   atol=2e-5,
                                   err_msg="step %d" % step_i)
    # parameters stay in lockstep too (spot-check two tensors)
    pa = pp.get_params()
    fa, _ = fused.get_params()
    for name in ("block0_q_weight", "lm_head_weight"):
        np.testing.assert_allclose(pa[name].asnumpy(),
                                   fa[name].asnumpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_pipeline_train_step_learns():
    """The pipelined trainer actually learns the shift task."""
    from incubator_mxnet_tpu.parallel.pipeline import PipelineTrainStep

    V, E, H, L, S, B, M = 16, 32, 4, 4, 12, 8, 4
    mesh = build_mesh({"pp": 4})
    import incubator_mxnet_tpu as mx

    mx.random.seed(3)
    pp = PipelineTrainStep(mesh, vocab_size=V, embed=E, heads=H,
                           num_layers=L, seq_len=S, batch_size=B,
                           num_microbatches=M, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3},
                           initializer=mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, V, (64, S)).astype(np.float32)
    data_b = tokens.reshape(8, B, S)
    label_b = (data_b + 1) % V
    loss = None
    for epoch in range(30):
        for i in range(8):
            loss = pp({"data": data_b[i], "softmax_label": label_b[i]})
        if loss < 0.05:
            break
    assert loss < 0.05, "pipelined LM failed to learn: %.3f" % loss
