"""Pipeline (pp) and expert (ep) parallelism — numeric contracts on the
virtual 8-device CPU mesh (SURVEY.md §4 philosophy: sharded result ==
single-device oracle)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.parallel import build_mesh
from incubator_mxnet_tpu.parallel.pipeline import pipeline_parallel_apply
from incubator_mxnet_tpu.parallel.moe import expert_parallel_moe, moe_ffn


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


@pytest.mark.parametrize("L,M", [(4, 8), (8, 8), (2, 3)])
def test_pipeline_matches_sequential(L, M):
    rng = np.random.RandomState(0)
    d = 16
    mesh = build_mesh({"pp": L})
    ws = jnp.asarray(rng.randn(L, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(L, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(M, 4, d).astype(np.float32))

    out = pipeline_parallel_apply(mesh, _stage_fn, (ws, bs), x)

    ref = np.asarray(x)
    for i in range(L):
        ref = np.tanh(ref @ np.asarray(ws[i]) + np.asarray(bs[i]))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_pipeline_differentiable():
    """Grads flow through the ppermute schedule (training path)."""
    rng = np.random.RandomState(1)
    L, M, d = 4, 4, 8
    mesh = build_mesh({"pp": L})
    ws = jnp.asarray(rng.randn(L, d, d).astype(np.float32) * 0.3)
    bs = jnp.zeros((L, d), jnp.float32)
    x = jnp.asarray(rng.randn(M, 2, d).astype(np.float32))

    def loss(ws, bs):
        return jnp.sum(pipeline_parallel_apply(mesh, _stage_fn,
                                               (ws, bs), x) ** 2)

    def loss_ref(ws, bs):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ ws[i] + bs[i])
        return jnp.sum(y ** 2)

    g = jax.grad(loss, argnums=(0, 1))(ws, bs)
    gr = jax.grad(loss_ref, argnums=(0, 1))(ws, bs)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def _moe_oracle(x, gate_w, w1s, w2s, k=1):
    """Dense single-device top-k MoE reference (unbounded capacity;
    k=1 uses the raw Switch gate, k>1 renormalizes GShard-style)."""
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(x)
    for t in range(x.shape[0]):
        top = np.argsort(-probs[t])[:k]
        gates = probs[t, top]
        if k > 1:
            gates = gates / gates.sum()
        for e, g in zip(top, gates):
            h = np.maximum(x[t] @ w1s[e], 0.0)
            out[t] += (h @ w2s[e]) * g
    return out


@pytest.mark.parametrize("E", [4, 8])
def test_moe_matches_dense(E):
    rng = np.random.RandomState(2)
    T, d, h = 8 * E, 16, 32  # T divisible by E (token sharding)
    mesh = build_mesh({"ep": E})
    x = rng.randn(T, d).astype(np.float32)
    gate_w = rng.randn(d, E).astype(np.float32)
    w1s = rng.randn(E, d, h).astype(np.float32) * 0.2
    w2s = rng.randn(E, h, d).astype(np.float32) * 0.2

    for k in (1, 2):
        # capacity ample => no drops => exact dense equivalence
        out, stats = expert_parallel_moe(
            mesh, jnp.asarray(x), jnp.asarray(gate_w),
            jnp.asarray(w1s), jnp.asarray(w2s), top_k=k,
            capacity_factor=float(E))
        ref = _moe_oracle(x, gate_w, w1s, w2s, k=k)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4, err_msg="k=%d" % k)
        assert float(stats["overflow"]) == 0.0


def test_moe_composes_with_dp():
    """dp × ep on one mesh: batch shards over dp, experts over ep."""
    from incubator_mxnet_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    import functools

    rng = np.random.RandomState(3)
    E, T, d, h = 4, 16, 8, 16
    mesh = build_mesh({"dp": 2, "ep": E})
    P = jax.sharding.PartitionSpec
    x = rng.randn(2 * T, d).astype(np.float32)
    gate_w = rng.randn(d, E).astype(np.float32)
    w1s = rng.randn(E, d, h).astype(np.float32) * 0.2
    w2s = rng.randn(E, h, d).astype(np.float32) * 0.2

    def body(x, gw, w1, w2):
        out, _ = moe_ffn(x, gw, jnp.squeeze(w1, 0), jnp.squeeze(w2, 0),
                         "ep", top_k=1, capacity_factor=float(E))
        return out

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
                   out_specs=P(("dp", "ep")))
    out = jax.jit(fn)(jnp.asarray(x), jnp.asarray(gate_w),
                      jnp.asarray(w1s), jnp.asarray(w2s))
    ref = _moe_oracle(x, gate_w, w1s, w2s)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# PipelineTrainStep: real pipelined training
# ---------------------------------------------------------------------------

def test_pipeline_train_step_matches_single_device():
    """A 4-stage pipelined LM (4 microbatches, fused head, adam) tracks
    the single-device FusedTrainStep loss curve on identical params and
    data — pipelined training is the same training."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel.pipeline import PipelineTrainStep

    V, E, H, L, S, B, M = 16, 16, 2, 4, 8, 8, 4
    rng = np.random.RandomState(0)

    net = mx.models.transformer_lm(vocab_size=V, embed=E, heads=H,
                                   num_layers=L, seq_len=S,
                                   batch_size=B, head="fused")
    mx.random.seed(11)
    fused = parallel.FusedTrainStep(
        net, {"data": (B, S)}, {"softmax_label": (B, S)},
        mesh=parallel.default_mesh(1), optimizer="adam",
        optimizer_params={"learning_rate": 3e-3},
        initializer=mx.initializer.Xavier())

    mesh = build_mesh({"pp": 4})
    pp = PipelineTrainStep(mesh, vocab_size=V, embed=E, heads=H,
                           num_layers=L, seq_len=S, batch_size=B,
                           num_microbatches=M, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
    # identical starting point: copy the fused step's params in
    arg_params, _ = fused.get_params()
    pp.set_params(arg_params)

    toks = rng.randint(0, V, (6, B, S)).astype(np.float32)
    labs = (toks + 1) % V
    for step_i in range(6):
        batch = {"data": toks[step_i], "softmax_label": labs[step_i]}
        outs = fused(batch)
        fused_loss = float(np.asarray(outs[0]).mean())
        pp_loss = pp(batch)
        np.testing.assert_allclose(pp_loss, fused_loss, rtol=2e-4,
                                   atol=2e-5,
                                   err_msg="step %d" % step_i)
    # parameters stay in lockstep too (spot-check two tensors)
    pa = pp.get_params()
    fa, _ = fused.get_params()
    for name in ("block0_q_weight", "lm_head_weight"):
        np.testing.assert_allclose(pa[name].asnumpy(),
                                   fa[name].asnumpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_pipeline_train_step_learns():
    """The pipelined trainer actually learns the shift task."""
    from incubator_mxnet_tpu.parallel.pipeline import PipelineTrainStep

    V, E, H, L, S, B, M = 16, 32, 4, 4, 12, 8, 4
    mesh = build_mesh({"pp": 4})
    import incubator_mxnet_tpu as mx

    mx.random.seed(3)
    pp = PipelineTrainStep(mesh, vocab_size=V, embed=E, heads=H,
                           num_layers=L, seq_len=S, batch_size=B,
                           num_microbatches=M, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3},
                           initializer=mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, V, (64, S)).astype(np.float32)
    data_b = tokens.reshape(8, B, S)
    label_b = (data_b + 1) % V
    loss = None
    for epoch in range(30):
        for i in range(8):
            loss = pp({"data": data_b[i], "softmax_label": label_b[i]})
        if loss < 0.05:
            break
    assert loss < 0.05, "pipelined LM failed to learn: %.3f" % loss


def test_moe_capacity_overflow_and_aux_loss():
    """Skewed routing: capacity drops the over-limit assignments
    (overflow accounted, dropped tokens contribute zero) and the
    load-balancing aux loss exceeds the balanced-routing value."""
    from incubator_mxnet_tpu.parallel.moe import expert_parallel_moe

    rng = np.random.RandomState(5)
    E, T, d, h = 4, 32, 8, 16
    mesh = build_mesh({"ep": E})
    # gate weights that route EVERY token to expert 0
    gate_w = np.zeros((d, E), np.float32)
    gate_w[:, 0] = 5.0
    x = np.abs(rng.randn(T, d)).astype(np.float32)  # positive features
    w1s = rng.randn(E, d, h).astype(np.float32) * 0.2
    w2s = rng.randn(E, h, d).astype(np.float32) * 0.2

    out, stats = expert_parallel_moe(
        mesh, jnp.asarray(x), jnp.asarray(gate_w), jnp.asarray(w1s),
        jnp.asarray(w2s), top_k=1, capacity_factor=1.0)
    # capacity_factor=1, k=1: per source C = ceil(T_local/E); expert 0
    # keeps C of T_local assignments per device => 1 - 1/E overflow
    np.testing.assert_allclose(float(stats["overflow"]), 1 - 1 / E,
                               rtol=1e-5)
    # dropped tokens produce EXACT zeros; kept ones are nonzero
    nz = (np.abs(np.asarray(out)).sum(-1) > 0)
    assert nz.sum() == T // E

    # aux loss: skewed >> balanced (identity-ish routing), and the
    # balanced value sits near the E*sum(f*P) = 1 optimum
    aux_skew = float(stats["aux_loss"])
    gate_bal = np.zeros((d, E), np.float32)
    _, stats_bal = expert_parallel_moe(
        mesh, jnp.asarray(rng.randn(T, d).astype(np.float32)),
        jnp.asarray(gate_bal), jnp.asarray(w1s), jnp.asarray(w2s),
        top_k=2, capacity_factor=2.0)
    aux_bal = float(stats_bal["aux_loss"])
    assert aux_skew > 2.0 * aux_bal
    assert 0.8 < aux_bal < 1.5


def test_moe_dispatch_is_capacity_bound():
    """The dispatch buffer is (E, C, d), not (E, T, d): jaxpr of the
    sharded program contains no T-by-E-by-d dense intermediate."""
    from incubator_mxnet_tpu.parallel.moe import moe_ffn
    from incubator_mxnet_tpu.parallel.mesh import shard_map_fn

    E, T, d, h = 4, 64, 8, 32  # h chosen so no weight shape collides
    mesh = build_mesh({"ep": E})
    P = jax.sharding.PartitionSpec

    def body(x, gw, w1, w2):
        out, stats = moe_ffn(x, gw, jnp.squeeze(w1, 0),
                             jnp.squeeze(w2, 0), "ep", top_k=1,
                             capacity_factor=1.25)
        return out

    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(P("ep"), P(), P("ep"), P("ep")),
                        out_specs=P("ep"))
    rng = np.random.RandomState(0)
    jaxpr = jax.make_jaxpr(fn)(
        jnp.asarray(rng.randn(T, d).astype(np.float32)),
        jnp.asarray(rng.randn(d, E).astype(np.float32)),
        jnp.asarray(rng.randn(E, d, h).astype(np.float32)),
        jnp.asarray(rng.randn(E, h, d).astype(np.float32)))
    t_local = T // E
    dense = "%d,%d,%d" % (E, t_local, d)
    assert dense not in str(jaxpr), \
        "dense (E, T, d) dispatch intermediate found"


def test_pipeline_train_step_composes_with_dp():
    """dp×pp on one mesh: batch shards over dp while stages pipeline
    over pp — same loss curve as the single-device step."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel.pipeline import PipelineTrainStep

    V, E, H, L, S, B, M = 16, 16, 2, 4, 8, 8, 2
    rng = np.random.RandomState(4)
    net = mx.models.transformer_lm(vocab_size=V, embed=E, heads=H,
                                   num_layers=L, seq_len=S,
                                   batch_size=B, head="fused")
    mx.random.seed(5)
    fused = parallel.FusedTrainStep(
        net, {"data": (B, S)}, {"softmax_label": (B, S)},
        mesh=parallel.default_mesh(1), optimizer="adam",
        optimizer_params={"learning_rate": 3e-3},
        initializer=mx.initializer.Xavier())

    mesh = build_mesh({"dp": 2, "pp": 4})
    pp = PipelineTrainStep(mesh, vocab_size=V, embed=E, heads=H,
                           num_layers=L, seq_len=S, batch_size=B,
                           num_microbatches=M, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
    arg_params, _ = fused.get_params()
    pp.set_params(arg_params)

    toks = rng.randint(0, V, (4, B, S)).astype(np.float32)
    labs = (toks + 1) % V
    for i in range(4):
        batch = {"data": toks[i], "softmax_label": labs[i]}
        outs = fused(batch)
        fused_loss = float(np.asarray(outs[0]).mean())
        pp_loss = pp(batch)
        np.testing.assert_allclose(pp_loss, fused_loss, rtol=2e-4,
                                   atol=2e-5, err_msg="step %d" % i)


# ---------------------------------------------------------------------------
# MoE as a MODEL capability (round-4 verdict #3): transformer_lm(moe_experts)
# ---------------------------------------------------------------------------

def test_moe_ffn_op_capacity_and_aux():
    """_contrib_MoEFFN: output shape preserved; a tiny capacity factor
    forces overflow; the balance aux is ~1 for a near-uniform router
    and grows when routing collapses."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.registry import OpContext, get_op

    op = get_op("_contrib_MoEFFN")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(6, 8, 16).astype(np.float32))
    gw = jnp.asarray(rng.randn(4, 16).astype(np.float32) * 0.01)
    w1 = jnp.asarray(rng.randn(4, 32, 16).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(4, 16, 32).astype(np.float32) * 0.1)
    (out, aux, over), _ = op.apply([x, gw, w1, w2], {},
                                   OpContext(is_train=True))
    assert out.shape == x.shape
    # near-uniform router (tiny gate weights): aux ~ 1, no overflow
    assert abs(float(aux) - 1.0) < 0.2
    assert float(over) < 0.2

    (_, _, over2), _ = op.apply(
        [x, gw, w1, w2], {"capacity_factor": "0.25"},
        OpContext(is_train=True))
    assert float(over2) > 0.4  # tiny capacity drops most assignments

    # collapsed router: positive inputs + one hot gate row push every
    # token to expert 0 -> aux approaches E (= 4 here), >> balanced 1
    x_pos = jnp.abs(x) + 0.1
    gw_bad = jnp.zeros((4, 16), jnp.float32).at[0].set(5.0)
    (_, aux_bad, _), _ = op.apply([x_pos, gw_bad, w1, w2],
                                  {"top_k": "1"},
                                  OpContext(is_train=True))
    assert float(aux_bad) > 2.0


def test_moe_transformer_lm_trains_on_dp_ep_mesh():
    """transformer_lm(moe_experts=4) through FusedTrainStep on a
    dp2 x ep4 mesh: expert weights shard P('ep'), the shift task is
    learned, balance/overflow stats surface every step, and the router
    (gate) weights actually train."""
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel

    net = mx.models.transformer_lm(
        vocab_size=32, embed=32, heads=2, num_layers=2, seq_len=16,
        batch_size=8, dtype="float32", head="fused", moe_experts=4)
    moe_args = [n for n in net.list_arguments() if "_moe_" in n]
    assert len(moe_args) == 6  # gate + w1 + w2 per layer
    P = jax.sharding.PartitionSpec
    mesh = parallel.build_mesh({"dp": 2, "ep": 4})
    part = {n: P("ep") for n in net.list_arguments() if "_moe_w" in n}
    mx.random.seed(0)
    step = parallel.FusedTrainStep(
        net, {"data": (8, 16)}, {"softmax_label": (8, 16)},
        mesh=mesh, optimizer="adam",
        optimizer_params={"learning_rate": 1e-2},
        initializer=mx.initializer.Xavier(), param_partition=part)
    # the expert stacks are genuinely ep-sharded
    assert not step.params["block0_moe_w1"].sharding \
        .is_fully_replicated
    gate0 = np.asarray(step.params["block0_moe_gate_weight"]).copy()
    rng = np.random.RandomState(0)
    data = rng.randint(0, 32, (8, 16)).astype(np.float32)
    labels = np.roll(data, -1, 1)
    first = last = None
    for _ in range(40):
        outs = step({"data": data, "softmax_label": labels})
        last = float(np.asarray(outs[0]).mean())
        if first is None:
            first = last
        aux = float(np.asarray(outs[1]))
        over = float(np.asarray(outs[2]))
        assert np.isfinite(aux) and 0.0 <= over <= 1.0
    assert last < first * 0.2, (first, last)
    # aux-loss gradients reached the router
    assert np.abs(np.asarray(step.params["block0_moe_gate_weight"])
                  - gate0).max() > 1e-6
