"""Per-op numeric test sweep — NN layers, RNN, sequence and contrib tiers,
plus the registry completeness check (``test_all_ops_covered``): every
public op in ``ops/registry.list_ops()`` must be exercised by a numeric
assert in the sweep or an explicitly named test file."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.ops.registry import get_op, list_ops, OpContext

from test_operator import apply_op, check_fwd, check_grad_fd


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


# ---------------------------------------------------------------------------
# FullyConnected / Convolution / Deconvolution
# ---------------------------------------------------------------------------

def test_fully_connected():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4).astype(np.float32)
    w = rng.randn(5, 12).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    want = x.reshape(2, 12).astype(np.float64) @ w.T + b
    check_fwd("FullyConnected", [x, w, b], want,
              {"num_hidden": "5"}, rtol=1e-4, atol=1e-4)
    # no_bias + flatten=False applies to the last axis only
    w2 = rng.randn(5, 4).astype(np.float32)
    check_fwd("FullyConnected", [x, w2],
              x.astype(np.float64) @ w2.T,
              {"num_hidden": "5", "no_bias": "1", "flatten": "0"},
              rtol=1e-4, atol=1e-4)
    check_grad_fd("FullyConnected", [x[:1], w[:, :12], b],
                  {"num_hidden": "5"}, wrt=(0, 1, 2))
    # shape inference back-infers the weight shape (simple_bind parity)
    op = get_op("FullyConnected")
    shapes, outs, _ = op.infer_shape([(2, 3, 4), None, None],
                                     {"num_hidden": "5"})
    assert shapes[1] == (5, 12) and outs[0] == (2, 5)


def _np_conv2d(x, w, b, stride, pad, dilate, groups=1):
    n, cin, h, wd = x.shape
    cout, cpg, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wd + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    xp = np.pad(x.astype(np.float64), [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    opg = cout // groups
    out = np.zeros((n, cout, oh, ow))
    for nn_ in range(n):
        for co in range(cout):
            g = co // opg
            for i in range(oh):
                for j in range(ow):
                    acc = 0.0
                    for ci in range(cpg):
                        for a in range(kh):
                            for bb in range(kw):
                                acc += xp[nn_, g * cpg + ci,
                                          i * sh + a * dh,
                                          j * sw + bb * dw] * w[co, ci, a, bb]
                    out[nn_, co, i, j] = acc + (b[co] if b is not None
                                                else 0.0)
    return out


def test_convolution():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    for name in ("Convolution", "Convolution_v1"):
        check_fwd(name, [x, w, b],
                  _np_conv2d(x, w, b, (1, 1), (0, 0), (1, 1)),
                  {"kernel": "(3, 3)", "num_filter": "3"},
                  rtol=1e-4, atol=1e-4)
    # stride + pad + dilate
    check_fwd("Convolution", [x, w, b],
              _np_conv2d(x, w, b, (2, 2), (1, 1), (1, 1)),
              {"kernel": "(3, 3)", "num_filter": "3", "stride": "(2, 2)",
               "pad": "(1, 1)"}, rtol=1e-4, atol=1e-4)
    check_fwd("Convolution", [x, w[:, :, :2, :2], b],
              _np_conv2d(x, w[:, :, :2, :2], b, (1, 1), (0, 0), (2, 2)),
              {"kernel": "(2, 2)", "num_filter": "3", "dilate": "(2, 2)"},
              rtol=1e-4, atol=1e-4)
    # grouped
    wg = rng.randn(4, 1, 2, 2).astype(np.float32)
    check_fwd("Convolution", [x, wg],
              _np_conv2d(x, wg, None, (1, 1), (0, 0), (1, 1), groups=2),
              {"kernel": "(2, 2)", "num_filter": "4", "num_group": "2",
               "no_bias": "1"}, rtol=1e-4, atol=1e-4)
    check_grad_fd("Convolution", [x[:, :, :3, :3], w[:2], b[:2]],
                  {"kernel": "(3, 3)", "num_filter": "2"}, wrt=(0, 1, 2))
    op = get_op("Convolution")
    shapes, outs, _ = op.infer_shape(
        [(1, 2, 5, 5), None, None],
        {"kernel": "(3, 3)", "num_filter": "3", "stride": "(2, 2)",
         "pad": "(1, 1)"})
    assert shapes[1] == (3, 2, 3, 3) and outs[0] == (1, 3, 3, 3)


def test_convolution_nhwc():
    """layout="NHWC" (reference ConvolutionParam layout option) matches
    the NCHW path on transposed data; weights stay OIHW in both layouts
    (initializer fan heuristics and checkpoints are layout-independent)."""
    rng = np.random.RandomState(30)
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    attrs = {"kernel": "(3, 3)", "num_filter": "4", "stride": "(2, 2)",
             "pad": "(1, 1)"}
    want = apply_op("Convolution", [x, w, b], attrs)[0]
    x_l = np.transpose(x, (0, 2, 3, 1))
    out = apply_op("Convolution", [x_l, w, b],
                   dict(attrs, layout="NHWC"))[0]
    np.testing.assert_allclose(np.transpose(out, (0, 3, 1, 2)), want,
                               rtol=1e-4, atol=1e-4)
    op = get_op("Convolution")
    shapes, outs, _ = op.infer_shape([(2, 6, 6, 3), None, None],
                                     dict(attrs, layout="NHWC"))
    assert shapes[1] == (4, 3, 3, 3) and outs[0] == (2, 3, 3, 4)


def test_pooling_nhwc():
    rng = np.random.RandomState(31)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    x_l = np.transpose(x, (0, 2, 3, 1))
    for ptype in ("max", "avg"):
        attrs = {"kernel": "(3, 3)", "stride": "(2, 2)", "pad": "(1, 1)",
                 "pool_type": ptype}
        want = apply_op("Pooling", [x], attrs)[0]
        out = apply_op("Pooling", [x_l], dict(attrs, layout="NHWC"))[0]
        np.testing.assert_allclose(np.transpose(out, (0, 3, 1, 2)), want,
                                   rtol=1e-5, atol=1e-5)
    want = apply_op("Pooling", [x], {"global_pool": "1"})[0]
    out = apply_op("Pooling", [x_l], {"global_pool": "1",
                                      "layout": "NHWC"})[0]
    np.testing.assert_allclose(np.transpose(out, (0, 3, 1, 2)), want,
                               rtol=1e-5)
    op = get_op("Pooling")
    _, outs, _ = op.infer_shape([(1, 5, 5, 2)],
                                {"kernel": "(3, 3)", "stride": "(2, 2)",
                                 "layout": "NHWC"})
    assert outs[0] == (1, 2, 2, 2)


def test_resnet_nhwc_matches_nchw():
    """models.resnet(layout="NHWC") is numerically the NCHW net on
    transposed data."""
    import incubator_mxnet_tpu as mx
    rng = np.random.RandomState(32)
    kw = dict(num_layers=20, num_classes=10, image_shape=(3, 32, 32))
    net_c = mx.models.resnet(**kw)
    net_l = mx.models.resnet(layout="NHWC", **kw)
    x = rng.randn(2, 3, 32, 32).astype(np.float32)
    shapes_c = {"data": (2, 3, 32, 32), "softmax_label": (2,)}
    shapes_l = {"data": (2, 32, 32, 3), "softmax_label": (2,)}
    ex_c = net_c.simple_bind(grad_req="null", **shapes_c)
    ex_l = net_l.simple_bind(grad_req="null", **shapes_l)
    rngp = np.random.RandomState(33)
    for n in sorted(ex_c.arg_dict):
        if n in shapes_c:
            continue
        v = rngp.uniform(-0.1, 0.1,
                         ex_c.arg_dict[n].shape).astype(np.float32)
        ex_c.arg_dict[n][:] = mx.nd.array(v)
        # weights are OIHW in BOTH layouts — same arrays load directly
        assert ex_l.arg_dict[n].shape == v.shape, (n, v.shape)
        ex_l.arg_dict[n][:] = mx.nd.array(v)
    ex_c.arg_dict["data"][:] = mx.nd.array(x)
    ex_l.arg_dict["data"][:] = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
    out_c = ex_c.forward(is_train=False)[0].asnumpy()
    out_l = ex_l.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_l, out_c, rtol=1e-4, atol=1e-5)


def test_resnet_s2d_nhwc_matches_nchw():
    """The space-to-depth stem merges channels in the same (bh, bw, c)
    order in both layouts, so the direct-weight-load contract holds for
    stem="s2d" too."""
    import incubator_mxnet_tpu as mx
    rng = np.random.RandomState(34)
    kw = dict(num_layers=18, num_classes=10, image_shape=(3, 64, 64),
              stem="s2d")
    net_c = mx.models.resnet(**kw)
    net_l = mx.models.resnet(layout="NHWC", **kw)
    x = rng.randn(2, 3, 64, 64).astype(np.float32)
    ex_c = net_c.simple_bind(grad_req="null", data=(2, 3, 64, 64),
                             softmax_label=(2,))
    ex_l = net_l.simple_bind(grad_req="null", data=(2, 64, 64, 3),
                             softmax_label=(2,))
    rngp = np.random.RandomState(35)
    for n in sorted(ex_c.arg_dict):
        if n in ("data", "softmax_label"):
            continue
        v = rngp.uniform(-0.1, 0.1,
                         ex_c.arg_dict[n].shape).astype(np.float32)
        assert ex_l.arg_dict[n].shape == v.shape, (n, v.shape)
        ex_c.arg_dict[n][:] = mx.nd.array(v)
        ex_l.arg_dict[n][:] = mx.nd.array(v)
    ex_c.arg_dict["data"][:] = mx.nd.array(x)
    ex_l.arg_dict["data"][:] = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
    out_c = ex_c.forward(is_train=False)[0].asnumpy()
    out_l = ex_l.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_l, out_c, rtol=1e-4, atol=1e-5)


def _np_deconv2d(x, w, stride, pad, kernel, adj=(0, 0)):
    n, cin, h, wd = x.shape
    _, cout, kh, kw = w.shape
    sh, sw = stride
    oh = (h - 1) * sh - 2 * pad[0] + kh + adj[0]
    ow = (wd - 1) * sw - 2 * pad[1] + kw + adj[1]
    full = np.zeros((n, cout, (h - 1) * sh + kh, (wd - 1) * sw + kw))
    for nn_ in range(n):
        for ci in range(cin):
            for i in range(h):
                for j in range(wd):
                    for a in range(kh):
                        for bb in range(kw):
                            full[nn_, :, i * sh + a, j * sw + bb] += \
                                x[nn_, ci, i, j] * w[ci, :, a, bb]
    return full[:, :, pad[0]:pad[0] + oh, pad[1]:pad[1] + ow]


def test_deconvolution():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 2, 3, 3).astype(np.float32)
    w = rng.randn(2, 3, 3, 3).astype(np.float32)  # (C_in, C_out, kh, kw)
    check_fwd("Deconvolution", [x, w],
              _np_deconv2d(x.astype(np.float64), w, (1, 1), (0, 0), (3, 3)),
              {"kernel": "(3, 3)", "num_filter": "3", "no_bias": "1"},
              rtol=1e-4, atol=1e-4)
    check_fwd("Deconvolution", [x, w],
              _np_deconv2d(x.astype(np.float64), w, (2, 2), (1, 1), (3, 3)),
              {"kernel": "(3, 3)", "num_filter": "3", "stride": "(2, 2)",
               "pad": "(1, 1)", "no_bias": "1"}, rtol=1e-4, atol=1e-4)
    check_grad_fd("Deconvolution", [x, w[:, :1]],
                  {"kernel": "(3, 3)", "num_filter": "1", "no_bias": "1"},
                  wrt=(0, 1))
    op = get_op("Deconvolution")
    shapes, outs, _ = op.infer_shape(
        [(1, 2, 3, 3), None],
        {"kernel": "(3, 3)", "num_filter": "3", "stride": "(2, 2)",
         "pad": "(1, 1)", "no_bias": "1"})
    assert outs[0] == (1, 3, 5, 5) and shapes[1] == (2, 3, 3, 3)


# ---------------------------------------------------------------------------
# activations / softmax family
# ---------------------------------------------------------------------------

def test_activation():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    x64 = x.astype(np.float64)
    cases = {"relu": np.maximum(x64, 0),
             "sigmoid": _sig(x64),
             "tanh": np.tanh(x64),
             "softrelu": np.log1p(np.exp(x64)),
             "softsign": x64 / (1 + np.abs(x64))}
    for act, want in cases.items():
        check_fwd("Activation", [x], want, {"act_type": act},
                  rtol=1e-4, atol=1e-4)
    check_grad_fd("Activation", [x[:2, :2] + 0.3], {"act_type": "tanh"})


def test_leaky_relu():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 2, 2).astype(np.float32)
    x64 = x.astype(np.float64)
    check_fwd("LeakyReLU", [x], np.where(x64 > 0, x64, 0.1 * x64),
              {"act_type": "leaky", "slope": "0.1"}, rtol=1e-4, atol=1e-4)
    check_fwd("LeakyReLU", [x],
              np.where(x64 > 0, x64, 0.3 * (np.exp(x64) - 1)),
              {"act_type": "elu", "slope": "0.3"}, rtol=1e-4, atol=1e-4)
    g = np.array([0.1, 0.2, 0.3], np.float32)
    check_fwd("LeakyReLU", [x, g],
              np.where(x64 > 0, x64, g.reshape(1, 3, 1, 1) * x64),
              {"act_type": "prelu"}, rtol=1e-4, atol=1e-4)
    # rrelu at inference uses the mean slope
    mid = (0.125 + 0.334) / 2
    check_fwd("LeakyReLU", [x], np.where(x64 > 0, x64, mid * x64),
              {"act_type": "rrelu"}, rtol=1e-4, atol=1e-4)
    # rrelu at train: slope per element within bounds
    out = apply_op("LeakyReLU", [x], {"act_type": "rrelu"},
                   is_train=True)[0]
    neg = x < 0
    ratio = out[neg] / x[neg]
    assert (ratio >= 0.125 - 1e-6).all() and (ratio <= 0.334 + 1e-6).all()


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def test_softmax_ops():
    rng = np.random.RandomState(5)
    x = rng.randn(3, 4).astype(np.float32)
    x64 = x.astype(np.float64)
    check_fwd("softmax", [x], _np_softmax(x64), rtol=1e-4, atol=1e-4)
    check_fwd("softmax", [x], _np_softmax(x64, 0), {"axis": "0"},
              rtol=1e-4, atol=1e-4)
    check_fwd("softmax", [x], _np_softmax(x64 / 2.0),
              {"temperature": "2"}, rtol=1e-4, atol=1e-4)
    check_fwd("log_softmax", [x], np.log(_np_softmax(x64)),
              rtol=1e-4, atol=1e-4)
    x4 = rng.randn(2, 3, 2, 2).astype(np.float32)
    x464 = x4.astype(np.float64)
    check_fwd("SoftmaxActivation", [x4], _np_softmax(x464, 1),
              {"mode": "channel"}, rtol=1e-4, atol=1e-4)
    flat = _np_softmax(x464.reshape(2, -1)).reshape(x4.shape)
    check_fwd("SoftmaxActivation", [x4], flat, rtol=1e-4, atol=1e-4)
    check_grad_fd("softmax", [x[:2, :3]])


def test_softmax_cross_entropy():
    """(1,)-shaped total batch loss with softmax-minus-onehot gradient
    (loss_binary_op.cc:29)."""
    rng = np.random.RandomState(9)
    data = rng.randn(6, 5).astype(np.float32)
    label = rng.randint(0, 5, 6).astype(np.float32)
    p = _np_softmax(data.astype(np.float64))
    want = -np.log(p[np.arange(6), label.astype(int)]).sum()
    outs = check_fwd("softmax_cross_entropy", [data, label],
                     np.array([want]), rtol=1e-5, atol=1e-5)
    assert outs[0].shape == (1,)
    check_fwd("SoftmaxCrossEntropy", [data, label], np.array([want]),
              rtol=1e-5, atol=1e-5)
    # analytic gradient: d(sum xent)/d(data) = p - onehot
    op = get_op("softmax_cross_entropy")
    g = jax.grad(lambda d: op.apply(
        [d, jnp.asarray(label)], {}, OpContext())[0][0].sum()
    )(jnp.asarray(data))
    oh = np.eye(5)[label.astype(int)]
    np.testing.assert_allclose(np.asarray(g), p - oh,
                               rtol=1e-4, atol=1e-4)
    check_grad_fd("softmax_cross_entropy", [data[:3], label[:3]])
    # mx.nd surface (the user-visible entry, VERDICT.md gap #1)
    import incubator_mxnet_tpu as mx

    nd_out = mx.nd.softmax_cross_entropy(mx.nd.array(data),
                                         mx.nd.array(label))
    np.testing.assert_allclose(nd_out.asnumpy(), [want], rtol=1e-5)


def test_softmax_output_grad():
    """Backward ignores the cotangent and emits (p - onehot)·grad_scale
    (softmax_output-inl.h)."""
    rng = np.random.RandomState(6)
    data = rng.randn(4, 5).astype(np.float32)
    label = np.array([1, 0, 4, 2], np.float32)
    p = _np_softmax(data.astype(np.float64))
    for name in ("SoftmaxOutput", "Softmax"):
        check_fwd(name, [data, label], p, rtol=1e-4, atol=1e-4)
    op = get_op("SoftmaxOutput")

    def loss(d, attrs):
        outs, _ = op.apply([d, jnp.asarray(label)], attrs, OpContext())
        return (outs[0] * 3.14).sum()  # cotangent must be ignored

    oh = np.eye(5)[label.astype(int)]
    g = jax.grad(lambda d: loss(d, {"grad_scale": "2"}))(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(g), (p - oh) * 2.0,
                               rtol=1e-4, atol=1e-4)
    # ignore_label + valid normalization
    lab2 = np.array([1, -1, 4, -1], np.float32)
    g = jax.grad(lambda d: (op.apply(
        [d, jnp.asarray(lab2)],
        {"use_ignore": "1", "ignore_label": "-1",
         "normalization": "valid"}, OpContext())[0][0]).sum()
    )(jnp.asarray(data))
    oh2 = np.zeros((4, 5))
    oh2[0, 1] = 1
    oh2[2, 4] = 1
    mask = np.array([1.0, 0, 1, 0])[:, None]
    np.testing.assert_allclose(np.asarray(g), (p - oh2) * mask / 2.0,
                               rtol=1e-4, atol=1e-4)


def test_regression_outputs():
    rng = np.random.RandomState(7)
    data = rng.randn(3, 4).astype(np.float32)
    label = rng.randn(3, 4).astype(np.float32)
    d64 = data.astype(np.float64)
    cases = {
        "LinearRegressionOutput": (d64, d64 - label),
        "MAERegressionOutput": (d64, np.sign(d64 - label)),
        "LogisticRegressionOutput": (_sig(d64), _sig(d64) - label),
    }
    for name, (fwd, bwd) in cases.items():
        check_fwd(name, [data, label], fwd, rtol=1e-4, atol=1e-4)
        op = get_op(name)
        g = jax.grad(lambda d, _o=op: (_o.apply(
            [d, jnp.asarray(label)], {"grad_scale": "2"},
            OpContext())[0][0]).sum())(jnp.asarray(data))
        np.testing.assert_allclose(np.asarray(g), bwd * 2.0 / 4,
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_svm_output():
    rng = np.random.RandomState(8)
    data = rng.randn(3, 4).astype(np.float32)
    label = np.array([0, 2, 1], np.float32)
    check_fwd("SVMOutput", [data, label], data)
    op = get_op("SVMOutput")
    d64 = data.astype(np.float64)
    oh = np.eye(4)[label.astype(int)]
    margin = 1.0
    score_y = (d64 * oh).sum(1, keepdims=True)
    viol = ((d64 - score_y + margin > 0) * (1 - oh)).astype(np.float64)
    want = viol - oh * viol.sum(1, keepdims=True)
    g = jax.grad(lambda d: (op.apply(
        [d, jnp.asarray(label)], {"use_linear": "1"},
        OpContext())[0][0]).sum())(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-4, atol=1e-4)
    m = np.maximum(0, d64 - score_y + margin) * (1 - oh)
    want2 = 2 * (m - oh * m.sum(1, keepdims=True))
    g2 = jax.grad(lambda d: (op.apply(
        [d, jnp.asarray(label)], {}, OpContext())[0][0]).sum()
    )(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(g2), want2, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# normalization layers
# ---------------------------------------------------------------------------

def test_batch_norm():
    rng = np.random.RandomState(9)
    x = rng.randn(4, 3, 2, 2).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 3).astype(np.float32)
    beta = rng.randn(3).astype(np.float32)
    mov_mean = np.zeros(3, np.float32)
    mov_var = np.ones(3, np.float32)
    eps, momentum = 1e-3, 0.9
    x64 = x.astype(np.float64)
    mean = x64.mean(axis=(0, 2, 3))
    var = x64.var(axis=(0, 2, 3))
    bs = (1, 3, 1, 1)
    for name in ("BatchNorm", "BatchNorm_v1"):
        op = get_op(name)
        outs, aux = op.apply(
            [jnp.asarray(v) for v in (x, gamma, beta, mov_mean, mov_var)],
            {"fix_gamma": "0"}, OpContext(is_train=True))
        want = (x64 - mean.reshape(bs)) / np.sqrt(var.reshape(bs) + eps) \
            * gamma.reshape(bs) + beta.reshape(bs)
        np.testing.assert_allclose(np.asarray(outs[0]), want,
                                   rtol=1e-3, atol=1e-3)
        # aux moving stats update
        np.testing.assert_allclose(np.asarray(aux[0]),
                                   momentum * mov_mean
                                   + (1 - momentum) * mean,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(aux[1]),
                                   momentum * mov_var
                                   + (1 - momentum) * var,
                                   rtol=1e-4, atol=1e-4)
    # fix_gamma=True (reference default) behaves as gamma == 1
    op = get_op("BatchNorm")
    outs, _ = op.apply(
        [jnp.asarray(v) for v in (x, gamma, beta, mov_mean, mov_var)],
        {}, OpContext(is_train=True))
    want1 = (x64 - mean.reshape(bs)) / np.sqrt(var.reshape(bs) + eps) \
        + beta.reshape(bs)
    np.testing.assert_allclose(np.asarray(outs[0]), want1,
                               rtol=1e-3, atol=1e-3)
    # inference uses the moving stats
    mm = rng.uniform(-0.1, 0.1, 3).astype(np.float32)
    mv = rng.uniform(0.8, 1.2, 3).astype(np.float32)
    outs, _ = op.apply(
        [jnp.asarray(v) for v in (x, gamma, beta, mm, mv)],
        {"fix_gamma": "0"}, OpContext(is_train=False))
    wantg = (x64 - mm.reshape(bs)) / np.sqrt(mv.reshape(bs) + eps) \
        * gamma.reshape(bs) + beta.reshape(bs)
    np.testing.assert_allclose(np.asarray(outs[0]), wantg,
                               rtol=1e-3, atol=1e-3)


def test_instance_layer_norm():
    rng = np.random.RandomState(10)
    x = rng.randn(2, 3, 4).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 3).astype(np.float32)
    beta = rng.randn(3).astype(np.float32)
    x64 = x.astype(np.float64)
    mean = x64.mean(axis=2, keepdims=True)
    var = x64.var(axis=2, keepdims=True)
    want = (x64 - mean) / np.sqrt(var + 1e-3) * gamma.reshape(1, 3, 1) \
        + beta.reshape(1, 3, 1)
    check_fwd("InstanceNorm", [x, gamma, beta], want, rtol=1e-3, atol=1e-3)

    gl = rng.uniform(0.5, 1.5, 4).astype(np.float32)
    bl = rng.randn(4).astype(np.float32)
    mean = x64.mean(axis=-1, keepdims=True)
    var = x64.var(axis=-1, keepdims=True)
    want = (x64 - mean) / np.sqrt(var + 1e-5) * gl.reshape(1, 1, 4) \
        + bl.reshape(1, 1, 4)
    check_fwd("LayerNorm", [x, gl, bl], want, rtol=1e-3, atol=1e-3)
    check_grad_fd("LayerNorm", [x[:1, :2], gl * 0 + 1.0, bl * 0],
                  wrt=(0, 1, 2), rtol=5e-2, atol=5e-2)


def test_lrn():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 5, 3, 3).astype(np.float32)
    alpha, beta, knorm, nsize = 1e-3, 0.75, 2.0, 3
    x64 = x.astype(np.float64)
    out = np.zeros_like(x64)
    half = nsize // 2
    for c in range(5):
        lo, hi = max(0, c - half), min(5, c + half + 1)
        win = (x64[:, lo:hi] ** 2).sum(axis=1)
        out[:, c] = x64[:, c] / (knorm + alpha / nsize * win) ** beta
    check_fwd("LRN", [x], out,
              {"alpha": str(alpha), "beta": str(beta),
               "knorm": str(knorm), "nsize": str(nsize)},
              rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# pooling / upsampling / dropout / crop
# ---------------------------------------------------------------------------

def _np_pool2d(x, kernel, stride, pad, ptype, convention="valid"):
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    num_h = h + 2 * ph - kh
    num_w = w + 2 * pw - kw
    if convention == "full":
        oh = int(np.ceil(num_h / sh)) + 1
        ow = int(np.ceil(num_w / sw)) + 1
    else:
        oh = num_h // sh + 1
        ow = num_w // sw + 1
    if ptype == "max":
        fill = -np.inf
    else:
        fill = 0.0
    ph2 = max(ph, (oh - 1) * sh + kh - h - ph)
    pw2 = max(pw, (ow - 1) * sw + kw - w - pw)
    xp = np.pad(x.astype(np.float64), [(0, 0), (0, 0), (ph, ph2), (pw, pw2)],
                constant_values=fill)
    out = np.zeros((n, c, oh, ow))
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if ptype == "max":
                out[:, :, i, j] = win.max(axis=(2, 3))
            elif ptype == "sum":
                out[:, :, i, j] = win.sum(axis=(2, 3))
            else:
                out[:, :, i, j] = win.sum(axis=(2, 3)) / (kh * kw)
    return out


def test_pooling():
    rng = np.random.RandomState(12)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    for name in ("Pooling", "Pooling_v1"):
        check_fwd(name, [x],
                  _np_pool2d(x, (2, 2), (2, 2), (0, 0), "max"),
                  {"kernel": "(2, 2)", "stride": "(2, 2)"},
                  rtol=1e-5, atol=1e-5)
    for ptype in ("avg", "sum"):
        check_fwd("Pooling", [x],
                  _np_pool2d(x, (3, 3), (2, 2), (1, 1), ptype),
                  {"kernel": "(3, 3)", "stride": "(2, 2)", "pad": "(1, 1)",
                   "pool_type": ptype}, rtol=1e-5, atol=1e-5)
    # full (ceil) convention gets an extra output position
    out = apply_op("Pooling", [x], {"kernel": "(2, 2)", "stride": "(2, 2)",
                                    "pooling_convention": "full"})[0]
    assert out.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(
        out, _np_pool2d(x, (2, 2), (2, 2), (0, 0), "max", "full"),
        rtol=1e-5)
    # global pooling
    check_fwd("Pooling", [x],
              x.astype(np.float64).max(axis=(2, 3), keepdims=True),
              {"global_pool": "1"}, rtol=1e-5, atol=1e-5)
    check_fwd("Pooling", [x],
              x.astype(np.float64).mean(axis=(2, 3), keepdims=True),
              {"global_pool": "1", "pool_type": "avg"},
              rtol=1e-5, atol=1e-5)
    check_grad_fd("Pooling", [x[:, :1, :4, :4]],
                  {"kernel": "(2, 2)", "stride": "(2, 2)",
                   "pool_type": "avg"})


def test_upsampling():
    rng = np.random.RandomState(13)
    x = rng.randn(1, 2, 3, 3).astype(np.float32)
    want = np.repeat(np.repeat(x, 2, axis=2), 2, axis=3)
    check_fwd("UpSampling", [x], want, {"scale": "2",
                                        "sample_type": "nearest"})
    # multi-input nearest: every input reaches (scale·h0, scale·w0), then
    # channel concat (or sum)
    y = rng.randn(1, 1, 6, 6).astype(np.float32)
    outs = apply_op("UpSampling", [x, y], {"scale": "2",
                                           "sample_type": "nearest"})
    assert outs[0].shape == (1, 3, 6, 6)
    np.testing.assert_allclose(outs[0][:, :2], want, rtol=1e-6)
    np.testing.assert_allclose(outs[0][:, 2:], y, rtol=1e-6)
    s = apply_op("UpSampling", [x[:, :1], y],
                 {"scale": "2", "sample_type": "nearest",
                  "multi_input_mode": "sum"})[0]
    np.testing.assert_allclose(s, want[:, :1] + y, rtol=1e-6)
    # bilinear: shape + corners preserved
    out = apply_op("UpSampling", [x], {"scale": "2",
                                       "sample_type": "bilinear"})[0]
    assert out.shape == (1, 2, 6, 6)


def test_dropout():
    rng = np.random.RandomState(14)
    x = (rng.rand(50, 50) + 0.5).astype(np.float32)
    # inference: identity
    check_fwd("Dropout", [x], x, {"p": "0.5"})
    # train: values are 0 or x/keep; keep-rate statistically right
    out = apply_op("Dropout", [x], {"p": "0.4"}, is_train=True)[0]
    keep = out != 0
    np.testing.assert_allclose(out[keep], (x / 0.6)[keep], rtol=1e-5)
    assert abs(keep.mean() - 0.6) < 0.05
    # mode=always applies at inference too
    out = apply_op("Dropout", [x], {"p": "0.4", "mode": "always"})[0]
    assert (out == 0).sum() > 0


def test_crop():
    x = np.arange(2 * 2 * 6 * 6, dtype=np.float32).reshape(2, 2, 6, 6)
    check_fwd("Crop", [x], x[:, :, 1:4, 2:6],
              {"offset": "(1, 2)", "h_w": "(3, 4)"})
    like = np.zeros((2, 2, 4, 4), np.float32)
    check_fwd("Crop", [x, like], x[:, :, 0:4, 0:4], {"num_args": "2"})
    check_fwd("Crop", [x], x[:, :, 1:5, 1:5],
              {"h_w": "(4, 4)", "center_crop": "1"})


# ---------------------------------------------------------------------------
# spatial transform family
# ---------------------------------------------------------------------------

def test_grid_generator():
    th, tw = 4, 5
    ys = np.linspace(-1, 1, th)
    xs = np.linspace(-1, 1, tw)
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)  # identity affine
    out = apply_op("GridGenerator", [theta],
                   {"transform_type": "affine",
                    "target_shape": "(%d, %d)" % (th, tw)})[0]
    np.testing.assert_allclose(out[0, 0], gx, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[0, 1], gy, rtol=1e-5, atol=1e-6)
    # warp: base grid + normalized flow
    flow = np.ones((1, 2, th, tw), np.float32)
    out = apply_op("GridGenerator", [flow],
                   {"transform_type": "warp",
                    "target_shape": "(%d, %d)" % (th, tw)})[0]
    np.testing.assert_allclose(out[0, 0], gx + 1.0 / (tw / 2.0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[0, 1], gy + 1.0 / (th / 2.0),
                               rtol=1e-5, atol=1e-6)


def test_bilinear_sampler_identity():
    rng = np.random.RandomState(15)
    data = rng.randn(1, 2, 4, 5).astype(np.float32)
    h, w = 4, 5
    gy, gx = np.meshgrid(np.linspace(-1, 1, h), np.linspace(-1, 1, w),
                         indexing="ij")
    grid = np.stack([gx, gy])[None].astype(np.float32)
    out = apply_op("BilinearSampler", [data, grid])[0]
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-4)


def test_spatial_transformer_identity():
    rng = np.random.RandomState(16)
    data = rng.randn(2, 3, 4, 4).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    out = apply_op("SpatialTransformer", [data, theta],
                   {"target_shape": "(4, 4)",
                    "transform_type": "affine",
                    "sampler_type": "bilinear"})[0]
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# sequence ops
# ---------------------------------------------------------------------------

def test_sequence_ops():
    rng = np.random.RandomState(17)
    x = rng.randn(4, 3, 2).astype(np.float32)  # (T, N, d)
    seq_len = np.array([2, 4, 1], np.float32)
    check_fwd("SequenceLast", [x, seq_len], x[-1])  # default: last step
    want = x[[1, 3, 0], np.arange(3)]
    check_fwd("SequenceLast", [x, seq_len], want,
              {"use_sequence_length": "1"})
    masked = x.copy()
    for b, L in enumerate(seq_len.astype(int)):
        masked[L:, b] = -1.0
    check_fwd("SequenceMask", [x, seq_len], masked,
              {"use_sequence_length": "1", "value": "-1"})
    check_fwd("SequenceMask", [x, seq_len], x)
    rev = x.copy()
    for b, L in enumerate(seq_len.astype(int)):
        rev[:L, b] = x[:L, b][::-1]
    check_fwd("SequenceReverse", [x, seq_len], rev,
              {"use_sequence_length": "1"})
    check_fwd("SequenceReverse", [x, seq_len], x[::-1])


# ---------------------------------------------------------------------------
# RNN op — numpy loop oracles per mode (cuDNN packing)
# ---------------------------------------------------------------------------

def _rnn_numpy(mode, x, wi, wh, bi, bh, h0, c0=None):
    T = x.shape[0]
    h, c = h0, c0
    ys = []
    for t in range(T):
        g = x[t] @ wi.T + bi + h @ wh.T + bh
        if mode == "rnn_tanh":
            h = np.tanh(g)
        elif mode == "rnn_relu":
            h = np.maximum(g, 0)
        elif mode == "lstm":
            i, f, gg, o = np.split(g, 4, axis=-1)
            c = _sig(f) * c + _sig(i) * np.tanh(gg)
            h = _sig(o) * np.tanh(c)
        elif mode == "gru":
            gx = x[t] @ wi.T + bi
            gh = h @ wh.T + bh
            rx, zx, nx = np.split(gx, 3, axis=-1)
            rh, zh, nh = np.split(gh, 3, axis=-1)
            r, z = _sig(rx + rh), _sig(zx + zh)
            n = np.tanh(nx + r * nh)
            h = (1 - z) * n + z * h
        ys.append(h)
    return np.stack(ys), h, c


@pytest.mark.parametrize("mode", ["rnn_tanh", "rnn_relu", "lstm", "gru"])
def test_rnn_modes(mode):
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_pack_weights
    rng = np.random.RandomState(18)
    T, N, I, H = 3, 2, 4, 5
    gates = {"rnn_tanh": 1, "rnn_relu": 1, "lstm": 4, "gru": 3}[mode]
    x = rng.randn(T, N, I).astype(np.float32)
    wi = (rng.randn(gates * H, I) * 0.3).astype(np.float32)
    wh = (rng.randn(gates * H, H) * 0.3).astype(np.float32)
    bi = (rng.randn(gates * H) * 0.1).astype(np.float32)
    bh = (rng.randn(gates * H) * 0.1).astype(np.float32)
    h0 = rng.randn(1, N, H).astype(np.float32)
    params = np.asarray(rnn_pack_weights(
        [(jnp.asarray(wi), jnp.asarray(wh), jnp.asarray(bi),
          jnp.asarray(bh))]))
    attrs = {"mode": mode, "state_size": str(H), "num_layers": "1",
             "state_outputs": "1"}
    ins = [x, params, h0]
    c0 = None
    if mode == "lstm":
        c0 = rng.randn(1, N, H).astype(np.float32)
        ins.append(c0)
    outs = apply_op("RNN", ins, attrs)
    want_y, want_h, want_c = _rnn_numpy(
        mode, x.astype(np.float64), wi, wh, bi, bh, h0[0],
        c0[0] if c0 is not None else None)
    np.testing.assert_allclose(outs[0], want_y, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(outs[1][0], want_h, rtol=1e-4, atol=1e-4)
    if mode == "lstm":
        np.testing.assert_allclose(outs[2][0], want_c, rtol=1e-4,
                                   atol=1e-4)


def test_rnn_bidirectional_shapes():
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size
    rng = np.random.RandomState(19)
    T, N, I, H = 3, 2, 4, 5
    n = rnn_param_size("lstm", 2, I, H, bidirectional=True)
    params = (rng.randn(n) * 0.1).astype(np.float32)
    x = rng.randn(T, N, I).astype(np.float32)
    h0 = np.zeros((4, N, H), np.float32)
    c0 = np.zeros((4, N, H), np.float32)
    outs = apply_op("RNN", [x, params, h0, c0],
                    {"mode": "lstm", "state_size": str(H),
                     "num_layers": "2", "bidirectional": "1",
                     "state_outputs": "1"})
    assert outs[0].shape == (T, N, 2 * H)
    assert outs[1].shape == (4, N, H) and outs[2].shape == (4, N, H)
    # reversed input mirrors the reverse-direction output
    op = get_op("RNN")
    shapes, outss, _ = op.infer_shape(
        [(T, N, I), None, None, None],
        {"mode": "lstm", "state_size": str(H), "num_layers": "2",
         "bidirectional": "1", "state_outputs": "1"})
    assert shapes[1] == (n,) and outss[0] == (T, N, 2 * H)


# ---------------------------------------------------------------------------
# contrib: quantize / fft / count_sketch
# ---------------------------------------------------------------------------

def test_quantize_dequantize():
    rng = np.random.RandomState(20)
    x = rng.uniform(-3, 8, (3, 4)).astype(np.float32)
    mn, mx = np.float32(-3.0), np.float32(8.0)
    scale = (mx - mn) / 255.0
    wantq = np.clip(np.round((x - mn) / scale), 0, 255).astype(np.uint8)
    for name in ("_contrib_quantize", "quantize"):
        outs = apply_op(name, [x, mn, mx])
        np.testing.assert_array_equal(outs[0], wantq)
        assert outs[0].dtype == np.uint8
    for name in ("_contrib_dequantize", "dequantize"):
        out = apply_op(name, [wantq, mn, mx])[0]
        np.testing.assert_allclose(out, wantq * scale + mn, rtol=1e-5)
        np.testing.assert_allclose(out, x, atol=scale)


def test_fft_ifft():
    rng = np.random.RandomState(21)
    x = rng.randn(2, 8).astype(np.float32)
    z = np.fft.fft(x.astype(np.float64), axis=-1)
    want = np.stack([z.real, z.imag], axis=-1).reshape(2, 16)
    for name in ("_contrib_fft", "fft"):
        check_fwd(name, [x], want, rtol=1e-4, atol=1e-4)
    for name in ("_contrib_ifft", "ifft"):
        # round trip recovers the input ×n (reference unnormalized ifft)
        f = apply_op("fft", [x])[0]
        back = apply_op(name, [f])[0]
        np.testing.assert_allclose(back, x * 8, rtol=1e-3, atol=1e-3)


def test_count_sketch():
    rng = np.random.RandomState(22)
    n, d, out_dim = 3, 6, 4
    x = rng.randn(n, d).astype(np.float32)
    h = rng.randint(0, out_dim, d).astype(np.float32)
    s = (rng.randint(0, 2, d) * 2 - 1).astype(np.float32)
    want = np.zeros((n, out_dim))
    for j in range(d):
        want[:, int(h[j])] += x[:, j].astype(np.float64) * s[j]
    for name in ("_contrib_count_sketch", "count_sketch"):
        check_fwd(name, [x, h, s], want, {"out_dim": str(out_dim)},
                  rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# registry completeness
# ---------------------------------------------------------------------------

# ops exercised by a numeric test in this file
NN_COVERED = {
    "FullyConnected", "Convolution", "Convolution_v1", "Deconvolution",
    "Activation", "LeakyReLU", "softmax", "log_softmax",
    "SoftmaxActivation", "SoftmaxOutput", "Softmax",
    "softmax_cross_entropy", "SoftmaxCrossEntropy",
    "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "SVMOutput", "BatchNorm", "BatchNorm_v1",
    "InstanceNorm", "LayerNorm", "LRN", "Pooling", "Pooling_v1",
    "UpSampling", "Dropout", "Crop", "GridGenerator", "BilinearSampler",
    "SpatialTransformer", "SequenceLast", "SequenceMask",
    "SequenceReverse", "RNN", "_contrib_quantize", "quantize",
    "_contrib_dequantize", "dequantize", "_contrib_fft", "fft",
    "_contrib_ifft", "ifft", "_contrib_count_sketch", "count_sketch",
}

# ops exercised (numeric asserts) by other dedicated test files
COVERED_ELSEWHERE = {
    "IdentityAttachKLSparseReg": "test_operator.py",
    "Custom": "test_custom_op.py",
    "_contrib_DotProductAttention": "test_transformer.py",
    "DotProductAttention": "test_transformer.py",
    "_contrib_SoftmaxXentHead": "test_transformer.py",
    "SoftmaxXentHead": "test_transformer.py",
    "Correlation": "test_contrib_vision.py",
    "_contrib_CTCLoss": "test_contrib_vision.py",
    "CTCLoss": "test_contrib_vision.py",
    "ctc_loss": "test_contrib_vision.py",
    "_contrib_PSROIPooling": "test_contrib_vision.py",
    "PSROIPooling": "test_contrib_vision.py",
    "_contrib_DeformablePSROIPooling": "test_contrib_vision.py",
    "DeformablePSROIPooling": "test_contrib_vision.py",
    "_contrib_DeformableConvolution": "test_contrib_vision.py",
    "DeformableConvolution": "test_contrib_vision.py",
    "_contrib_krprod": "test_contrib_vision.py",
    "khatri_rao": "test_contrib_vision.py",
    "MultiBoxPrior": "test_detection.py",
    "MultiBoxTarget": "test_detection.py",
    "MultiBoxDetection": "test_detection.py",
    "_contrib_MultiBoxPrior": "test_detection.py",
    "_contrib_MultiBoxTarget": "test_detection.py",
    "_contrib_MultiBoxDetection": "test_detection.py",
    "Proposal": "test_detection.py",
    "_contrib_Proposal": "test_detection.py",
    "_contrib_MultiProposal": "test_detection.py",
    "ROIPooling": "test_detection.py",
    "_contrib_ROIPooling": "test_detection.py",
    "_contrib_MoEFFN": "test_pipeline_moe.py",
    "MoEFFN": "test_pipeline_moe.py",
}


def test_all_ops_covered():
    """Every public op in the registry is exercised by a numeric assert —
    the reference's test_operator.py contract (SURVEY.md §4)."""
    import test_operator as top

    covered = (set(top.UNARY_CASES) | set(top.BINARY_CASES)
               | set(top.SCALAR_CASES) | set(top.REDUCE_CASES)
               | top.EXTRA_COVERED | NN_COVERED | set(COVERED_ELSEWHERE))
    missing = sorted(set(list_ops()) - covered)
    assert not missing, ("ops with no numeric test coverage: %s — add a "
                         "sweep entry" % missing)
    # integrity: 'covered elsewhere' claims point at files that actually
    # mention the op
    here = os.path.dirname(os.path.abspath(__file__))
    for name, fname in COVERED_ELSEWHERE.items():
        with open(os.path.join(here, fname)) as f:
            text = f.read()
        base = name.replace("_contrib_", "")
        assert name in text or base in text, (name, fname)
    # nothing claimed as covered that isn't registered
    ghost = sorted((covered - set(list_ops())))
    assert not ghost, "coverage table names unregistered ops: %s" % ghost


def test_batchnorm_ghost_sample_stats():
    """ghost_sample=k: statistics come from the first batch/k rows only
    (the stat reduce reads 1/k of the activation); normalization covers
    the full batch.  ghost_sample=1 is exact today's behavior."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.registry import OpContext, get_op

    rng = np.random.RandomState(0)
    x = rng.randn(8, 4, 3, 3).astype(np.float32)
    op = get_op("BatchNorm")
    gamma = jnp.ones(4)
    beta = jnp.zeros(4)
    mm, mv = jnp.zeros(4), jnp.ones(4)

    def run(attrs, xin):
        (out,), _ = op.apply([jnp.asarray(xin), gamma, beta, mm, mv],
                             dict(attrs, fix_gamma="False", eps="1e-5"),
                             OpContext(is_train=True))
        return np.asarray(out)

    # ghost stats over the first half == full stats of a batch whose
    # second half duplicates the first
    x_dup = np.concatenate([x[:4], x[:4]])
    ghost = run({"ghost_sample": "2"}, x_dup)
    full_half = run({}, x[:4])
    np.testing.assert_allclose(ghost[:4], full_half, rtol=1e-5,
                               atol=1e-6)
    # and differs from full-batch stats when halves differ
    assert np.abs(run({"ghost_sample": "2"}, x)
                  - run({}, x)).max() > 1e-4


def test_layernorm_large_offset_variance():
    """Single-pass LN statistics survive a large common offset (the
    E[x²]−mean² cancellation case): mean≈300, std≈0.05 must normalize
    to unit variance, matching the two-pass oracle."""
    from incubator_mxnet_tpu.ops.registry import OpContext, get_op

    rng = np.random.RandomState(0)
    x = (300.0 + 0.05 * rng.randn(4, 64)).astype(np.float32)
    op = get_op("LayerNorm")
    (y,), _ = op.apply([jnp.asarray(x), jnp.ones(64), jnp.zeros(64)],
                       {"axis": "-1"}, OpContext(is_train=True))
    y = np.asarray(y)
    ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(
        x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, ref, rtol=2e-2, atol=2e-2)
    assert 0.9 < y.std() < 1.1
