"""Seeded lock-discipline violations for tests/test_analysis.py.

Never imported — parsed by the static lock checker only.
"""
import queue
import threading


class Inverted:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.q = queue.Queue()

    def ab(self):
        with self.a:
            with self.b:  # SEED:ab
                return 1

    def ba(self):
        with self.b:
            with self.a:  # SEED:ba
                return 2

    def drain(self):
        with self.a:
            return self.q.get()  # SEED:blocking

    def helper_takes_b(self):
        with self.b:  # SEED:via-helper
            return 3

    def a_then_helper(self):
        # the edge a -> b must also be found through the method call
        with self.a:
            return self.helper_takes_b()
