"""Seeded data-race violations for tests/test_analysis.py.

Never imported — parsed by the static race checker only.
"""
import threading


class UnlockedCounter:
    """Shared counter mutated by both roles with no common lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.hits = 0
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def _worker(self):
        while True:
            self.hits += 1  # SEED:unlocked-write

    def snapshot(self):
        return self.hits


class CheckThenAct:
    """Guard read outside the lock taken for the dependent write."""

    def __init__(self):
        self.lock = threading.Lock()
        self.items = []
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def _worker(self):
        while True:
            with self.lock:
                self.items.append(1)

    def take(self):
        if self.items:  # SEED:check-then-act
            with self.lock:
                return self.items.pop()
        return None


class InitEscape:
    """Attribute published to the thread after it already started."""

    def __init__(self):
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()
        self.config = {"ready": True}  # SEED:init-escape

    def _worker(self):
        while not self.config:
            pass


class PublishedStats:
    """Public mirror updated on the worker with no lock — external
    readers are an implicit unlocked role."""

    def __init__(self):
        self.processed = 0
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def _worker(self):
        while True:
            self.processed += 1  # SEED:public-mirror


class GuardedCounter:
    """Every access under the one lock — the pass must stay quiet."""

    def __init__(self):
        self.lock = threading.Lock()
        self.hits = 0
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def _worker(self):
        while True:
            with self.lock:
                self.hits += 1  # SEED:ok-guarded

    def snapshot(self):
        with self.lock:
            return self.hits


class SuppressedFlag:
    """A by-design GIL-atomic flag with a written justification."""

    def __init__(self):
        self.running = True
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def _worker(self):
        while self.running:
            pass

    def stop(self):
        # tp-lint: disable=race-unlocked-shared-state -- GIL-atomic bool
        self.running = False  # SEED:suppressed
