"""Seeded tracing-hazard violations for tests/test_analysis.py.

Never imported — parsed by the AST lint only.  Each violation carries a
``SEED:<tag>`` marker comment the test resolves to a line number.
"""
import os

import jax
import numpy as np


@jax.jit
def leaky(x, y):
    lr = float(os.environ.get("TP_LR", "0.1"))  # SEED:env
    v = x.sum()
    host = v.item()  # SEED:item
    if y > 0:  # SEED:branch
        y = y + host
    z = np.asarray(y)  # SEED:asarray
    return z * lr


@jax.jit
def shape_branch_is_fine(x):
    # static metadata: no finding expected on this branch
    if x.ndim > 1:  # SEED:ok-branch
        x = x.reshape((x.shape[0], -1))
    return x.sum()


step = jax.jit(lambda p, g: p - 0.1 * g, donate_argnums=(0,))


def train(p, g):
    new_p = step(p, g)
    stale = p + 1.0  # SEED:donated
    return new_p, stale


def train_ok(p, g):
    p = step(p, g)  # reassignment makes reuse safe
    return p + 1.0  # SEED:ok-donated
