"""Per-op numeric test sweep — tensor tiers.

Reference analog: ``tests/python/unittest/test_operator.py`` (~3.5 kLoC)
philosophy (SURVEY.md §4): every op checked against a numpy oracle, with
finite-difference gradient checks for the differentiable ones.  Table-driven
rather than 3.5 kLoC of prose; ``test_all_ops_covered`` (in
test_operator_nn.py) asserts that EVERY registered public op is exercised
by this sweep or an explicitly named test file.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from scipy import special as sps

from incubator_mxnet_tpu.ops.registry import get_op, list_ops, OpContext


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

def apply_op(name, inputs, attrs=None, is_train=False, seed=0):
    op = get_op(name)
    rng = jax.random.PRNGKey(seed) if op.needs_rng else None
    outs, _ = op.apply([jnp.asarray(i) for i in inputs], attrs or {},
                       OpContext(is_train=is_train, rng=rng))
    return [np.asarray(o) for o in outs]


def check_fwd(name, inputs, expected, attrs=None, rtol=1e-5, atol=1e-5,
              is_train=False, seed=0):
    outs = apply_op(name, inputs, attrs, is_train=is_train, seed=seed)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) >= len(expected), (name, len(outs), len(expected))
    for got, want in zip(outs, expected):
        np.testing.assert_allclose(
            got.astype(np.float64), np.asarray(want).astype(np.float64),
            rtol=rtol, atol=atol, err_msg="op %s forward mismatch" % name)
    return outs


def check_grad_fd(name, inputs, attrs=None, wrt=(0,), eps=1e-3, rtol=2e-2,
                  atol=2e-2, is_train=True, seed=0, out_index=None):
    """jax.grad of a random projection of the op's outputs vs central
    finite differences — the ``check_numeric_gradient`` contract applied
    directly at the op level (fast: no executor bind per op)."""
    op = get_op(name)
    rng = jax.random.PRNGKey(seed) if op.needs_rng else None
    ctx = OpContext(is_train=is_train, rng=rng)
    base = [jnp.asarray(np.asarray(x, np.float64).astype(np.float32))
            for x in inputs]
    outs0, _ = op.apply(base, attrs or {}, ctx)
    sel = range(len(outs0)) if out_index is None else [out_index]
    proj = [np.random.RandomState(7).normal(
        0, 1, size=np.shape(outs0[i])).astype(np.float64) for i in sel]

    def f(*xs):
        ins = list(base)
        for i, x in zip(wrt, xs):
            ins[i] = x
        outs, _ = op.apply(ins, attrs or {}, ctx)
        return sum((outs[i].astype(jnp.float64) * p).sum()
                   for i, p in zip(sel, proj))

    args = [base[i] for i in wrt]
    sym_grads = jax.grad(f, argnums=tuple(range(len(wrt))))(*args)
    for k, i in enumerate(wrt):
        x0 = np.asarray(base[i], np.float64)
        num = np.zeros_like(x0)
        flat, nflat = x0.reshape(-1), num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps / 2
            args_p = list(args)
            args_p[k] = jnp.asarray(x0.astype(np.float32))
            fp = float(f(*args_p))
            flat[j] = orig - eps / 2
            args_m = list(args)
            args_m[k] = jnp.asarray(x0.astype(np.float32))
            fm = float(f(*args_m))
            nflat[j] = (fp - fm) / eps
            flat[j] = orig
        np.testing.assert_allclose(
            np.asarray(sym_grads[k], np.float64), num, rtol=rtol, atol=atol,
            err_msg="op %s grad[arg %d] mismatch vs finite diff" % (name, i))


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

UNARY_CASES = {
    # name: (numpy fn, (lo, hi), grad_check)
    "negative": (lambda x: -x, (-2, 2), True),
    "_np_negative": (lambda x: -x, (-2, 2), True),
    "abs": (np.abs, (0.5, 2), True),
    "sign": (np.sign, (-2, 2), False),
    "round": (np.round, (-2, 2), False),
    "rint": (np.rint, (-2, 2), False),
    "ceil": (np.ceil, (-2, 2), False),
    "floor": (np.floor, (-2, 2), False),
    "trunc": (np.trunc, (-2, 2), False),
    "fix": (np.trunc, (-2, 2), False),
    "square": (np.square, (-2, 2), True),
    "sqrt": (np.sqrt, (0.5, 3), True),
    "rsqrt": (lambda x: 1 / np.sqrt(x), (0.5, 3), True),
    "cbrt": (np.cbrt, (0.5, 3), True),
    "rcbrt": (lambda x: 1 / np.cbrt(x), (0.5, 3), True),
    "exp": (np.exp, (-2, 2), True),
    "log": (np.log, (0.5, 3), True),
    "log10": (np.log10, (0.5, 3), True),
    "log2": (np.log2, (0.5, 3), True),
    "log1p": (np.log1p, (-0.5, 2), True),
    "expm1": (np.expm1, (-2, 2), True),
    "sin": (np.sin, (-2, 2), True),
    "cos": (np.cos, (-2, 2), True),
    "tan": (np.tan, (-1, 1), True),
    "arcsin": (np.arcsin, (-0.9, 0.9), True),
    "arccos": (np.arccos, (-0.9, 0.9), True),
    "arctan": (np.arctan, (-2, 2), True),
    "sinh": (np.sinh, (-2, 2), True),
    "cosh": (np.cosh, (-2, 2), True),
    "tanh": (np.tanh, (-2, 2), True),
    "arcsinh": (np.arcsinh, (-2, 2), True),
    "arccosh": (np.arccosh, (1.1, 3), True),
    "arctanh": (np.arctanh, (-0.9, 0.9), True),
    "degrees": (np.degrees, (-2, 2), True),
    "radians": (np.radians, (-2, 2), True),
    "reciprocal": (lambda x: 1 / x, (0.5, 3), True),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), (-2, 2), True),
    "softsign": (lambda x: x / (1 + np.abs(x)), (0.2, 2), True),
    "relu": (lambda x: np.maximum(x, 0), (0.2, 2), True),
    "gamma": (sps.gamma, (0.5, 3), True),
    "gammaln": (sps.gammaln, (0.5, 3), True),
    "erf": (sps.erf, (-2, 2), True),
    "erfinv": (sps.erfinv, (-0.9, 0.9), True),
    "logical_not": (lambda x: (x == 0).astype(x.dtype), (-2, 2), False),
    "ones_like": (np.ones_like, (-2, 2), False),
    "zeros_like": (np.zeros_like, (-2, 2), False),
    "identity": (lambda x: x, (-2, 2), True),
    "_copy": (lambda x: x, (-2, 2), True),
}


@pytest.mark.parametrize("name", sorted(UNARY_CASES))
def test_unary(name):
    np_fn, (lo, hi), grad = UNARY_CASES[name]
    rng = np.random.RandomState(hash(name) % 2**31)
    x = rng.uniform(lo, hi, (3, 4)).astype(np.float32)
    check_fwd(name, [x], np_fn(x.astype(np.float64)), rtol=1e-4, atol=1e-4)
    if grad:
        check_grad_fd(name, [rng.uniform(lo, hi, (2, 3))])


def test_block_grad_zero():
    for name in ("BlockGrad", "stop_gradient"):
        x = np.array([[1.0, -2.0]], np.float32)
        check_fwd(name, [x], x)
        g = jax.grad(lambda v: get_op(name).apply(
            [v], {}, OpContext())[0][0].sum())(jnp.asarray(x))
        assert np.all(np.asarray(g) == 0.0), name


def test_make_loss_grad():
    """forward identity; grad = grad_scale / norm regardless of cotangent
    (make_loss-inl.h:91-118)."""
    x = np.array([[1.0, -2.0], [0.5, 3.0]], np.float32)
    for name in ("make_loss", "MakeLoss"):
        check_fwd(name, [x], x)
    g = jax.grad(lambda v: (get_op("make_loss").apply(
        [v], {"grad_scale": "3", "normalization": "batch"},
        OpContext())[0][0] * 7.0).sum())(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), np.full_like(x, 3.0 / 2))


def test_cast():
    x = np.array([[1.6, -2.3]], np.float32)
    for name in ("Cast", "cast"):
        out = apply_op(name, [x], {"dtype": "int32"})[0]
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, x.astype(np.int32))
    out = apply_op("Cast", [x], {"dtype": "float16"})[0]
    assert out.dtype == np.float16


def test_clip():
    x = np.linspace(-3, 3, 12).reshape(3, 4).astype(np.float32)
    check_fwd("clip", [x], np.clip(x, -1, 2),
              {"a_min": "-1", "a_max": "2"})
    check_grad_fd("clip", [np.array([[-2.0, 0.5, 3.0]])],
                  {"a_min": "-1", "a_max": "2"})


def test_smooth_l1():
    x = np.array([[-2.0, -0.3, 0.0, 0.4, 1.5]], np.float32)
    sigma = 2.0
    s2 = sigma * sigma
    want = np.where(np.abs(x) < 1 / s2, 0.5 * s2 * x * x,
                    np.abs(x) - 0.5 / s2)
    check_fwd("smooth_l1", [x], want, {"scalar": str(sigma)})
    check_grad_fd("smooth_l1", [np.array([[-1.0, 0.1, 0.8]])],
                  {"scalar": "2"})


# ---------------------------------------------------------------------------
# binary / scalar / broadcast
# ---------------------------------------------------------------------------

def _np_logical(op):
    return lambda a, b: op((a != 0), (b != 0)).astype(a.dtype)


BINARY_CASES = {
    "elemwise_add": (np.add, True), "_plus": (np.add, True),
    "_add": (np.add, True), "broadcast_add": (np.add, True),
    "broadcast_plus": (np.add, True),
    "elemwise_sub": (np.subtract, True), "_minus": (np.subtract, True),
    "_sub": (np.subtract, True), "broadcast_sub": (np.subtract, True),
    "broadcast_minus": (np.subtract, True),
    "elemwise_mul": (np.multiply, True), "_mul": (np.multiply, True),
    "broadcast_mul": (np.multiply, True),
    "elemwise_div": (np.divide, True), "_div": (np.divide, True),
    "broadcast_div": (np.divide, True),
    "_mod": (np.mod, False), "broadcast_mod": (np.mod, False),
    "_power": (np.power, True), "_pow": (np.power, True),
    "broadcast_power": (np.power, True),
    "_maximum": (np.maximum, False), "broadcast_maximum": (np.maximum, False),
    "_minimum": (np.minimum, False), "broadcast_minimum": (np.minimum, False),
    "_hypot": (np.hypot, True), "broadcast_hypot": (np.hypot, True),
    "_equal": (lambda a, b: (a == b).astype(a.dtype), False),
    "broadcast_equal": (lambda a, b: (a == b).astype(a.dtype), False),
    "_not_equal": (lambda a, b: (a != b).astype(a.dtype), False),
    "broadcast_not_equal": (lambda a, b: (a != b).astype(a.dtype), False),
    "_greater": (lambda a, b: (a > b).astype(a.dtype), False),
    "broadcast_greater": (lambda a, b: (a > b).astype(a.dtype), False),
    "_greater_equal": (lambda a, b: (a >= b).astype(a.dtype), False),
    "broadcast_greater_equal":
        (lambda a, b: (a >= b).astype(a.dtype), False),
    "_lesser": (lambda a, b: (a < b).astype(a.dtype), False),
    "broadcast_lesser": (lambda a, b: (a < b).astype(a.dtype), False),
    "_lesser_equal": (lambda a, b: (a <= b).astype(a.dtype), False),
    "broadcast_lesser_equal":
        (lambda a, b: (a <= b).astype(a.dtype), False),
    "_logical_and": (_np_logical(np.logical_and), False),
    "broadcast_logical_and": (_np_logical(np.logical_and), False),
    "_logical_or": (_np_logical(np.logical_or), False),
    "broadcast_logical_or": (_np_logical(np.logical_or), False),
    "_logical_xor": (_np_logical(np.logical_xor), False),
    "broadcast_logical_xor": (_np_logical(np.logical_xor), False),
}


@pytest.mark.parametrize("name", sorted(BINARY_CASES))
def test_binary(name):
    np_fn, grad = BINARY_CASES[name]
    rng = np.random.RandomState(hash(name) % 2**31)
    a = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    b = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    check_fwd(name, [a, b], np_fn(a.astype(np.float64),
                                  b.astype(np.float64)),
              rtol=1e-4, atol=1e-4)
    if name.startswith("broadcast"):
        # true broadcast shapes
        a2 = rng.uniform(0.5, 2, (2, 1, 3)).astype(np.float32)
        b2 = rng.uniform(0.5, 2, (1, 4, 1)).astype(np.float32)
        check_fwd(name, [a2, b2], np_fn(a2.astype(np.float64),
                                        b2.astype(np.float64)),
                  rtol=1e-4, atol=1e-4)
    if grad:
        check_grad_fd(name, [rng.uniform(0.7, 1.5, (2, 3)),
                             rng.uniform(0.7, 1.5, (2, 3))], wrt=(0, 1))


SCALAR_CASES = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: np.mod(x, s),
    "_rmod_scalar": lambda x, s: np.mod(s, x),
    "_power_scalar": lambda x, s: np.power(x, s),
    "_rpower_scalar": lambda x, s: np.power(s, x),
    "_maximum_scalar": lambda x, s: np.maximum(x, s),
    "_minimum_scalar": lambda x, s: np.minimum(x, s),
    "_hypot_scalar": lambda x, s: np.hypot(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}


@pytest.mark.parametrize("name", sorted(SCALAR_CASES))
def test_binary_scalar(name):
    np_fn = SCALAR_CASES[name]
    rng = np.random.RandomState(hash(name) % 2**31)
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    s = 1.5
    check_fwd(name, [x], np_fn(x.astype(np.float64), s),
              {"scalar": str(s)}, rtol=1e-4, atol=1e-4)
    # integer array + whole scalar stays integer (reference dtype rule)
    xi = np.arange(6, dtype=np.int32).reshape(2, 3) + 1
    out = apply_op(name, [xi], {"scalar": "2"})[0]
    assert out.dtype == np.int32, name


def test_int_division_exact():
    """Integer division stays in the integer domain — float32 round-trip
    corrupts quotients at |v| >= 2^24 (mshadow divides with C semantics)."""
    big = np.array([2**24 + 1, -(2**24 + 3), 7], np.int32)
    for name in ("_div_scalar",):
        out = apply_op(name, [big], {"scalar": "1"})[0]
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, big)
    out = apply_op("_div_scalar", [big], {"scalar": "2"})[0]
    np.testing.assert_array_equal(out, np.array(
        [(2**24 + 1) // 2, -((2**24 + 3) // 2), 3], np.int32))  # trunc
    den = np.ones(3, np.int32)
    for name in ("elemwise_div", "_div", "broadcast_div"):
        out = apply_op(name, [big, den])[0]
        assert out.dtype == np.int32, name
        np.testing.assert_array_equal(out, big)
    out = apply_op("_rdiv_scalar", [np.array([3], np.int32)],
                   {"scalar": str(2**24 + 2)})[0]
    np.testing.assert_array_equal(out, [(2**24 + 2) // 3])


def test_add_n_variants():
    rng = np.random.RandomState(0)
    arrs = [rng.randn(2, 3).astype(np.float32) for _ in range(4)]
    want = np.sum(arrs, axis=0)
    for name in ("add_n", "ElementWiseSum", "_sum"):
        check_fwd(name, arrs, want)
    check_grad_fd("add_n", arrs[:2], wrt=(0, 1))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

REDUCE_CASES = {
    "sum": np.sum, "sum_axis": np.sum, "mean": np.mean, "prod": np.prod,
    "max": np.max, "max_axis": np.max, "min": np.min, "min_axis": np.min,
    "nansum": np.nansum, "nanprod": np.nanprod,
}


@pytest.mark.parametrize("name", sorted(REDUCE_CASES))
def test_reduce(name):
    np_fn = REDUCE_CASES[name]
    rng = np.random.RandomState(hash(name) % 2**31)
    x = rng.uniform(0.5, 1.5, (2, 3, 4)).astype(np.float32)
    if name.startswith("nan"):
        x[0, 1, 2] = np.nan
    x64 = x.astype(np.float64)
    check_fwd(name, [x], np_fn(x64), rtol=1e-4, atol=1e-4)  # all axes
    check_fwd(name, [x], np_fn(x64, axis=(0, 2)), {"axis": "(0, 2)"},
              rtol=1e-4, atol=1e-4)
    check_fwd(name, [x], np_fn(x64, axis=1, keepdims=True),
              {"axis": "1", "keepdims": "1"}, rtol=1e-4, atol=1e-4)
    # exclude reduces over the complement axes
    check_fwd(name, [x], np_fn(x64, axis=(0, 2)),
              {"axis": "1", "exclude": "1"}, rtol=1e-4, atol=1e-4)
    if name in ("sum", "mean"):
        check_grad_fd(name, [rng.uniform(0.5, 1.5, (2, 3))], {"axis": "1"})


def test_norm():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    check_fwd("norm", [x], np.sqrt(np.sum(np.square(
        x.astype(np.float64)))), rtol=1e-4, atol=1e-4)
    check_fwd("norm", [x], np.abs(x.astype(np.float64)).sum(axis=1),
              {"ord": "1", "axis": "1"}, rtol=1e-4, atol=1e-4)
    check_grad_fd("norm", [rng.uniform(0.5, 1.5, (2, 3))])


def test_argmax_argmin():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 5).astype(np.float32)
    check_fwd("argmax", [x], np.argmax(x))              # flattened default
    check_fwd("argmax", [x], np.argmax(x, 1), {"axis": "1"})
    check_fwd("argmax", [x], np.argmax(x, 1)[:, None],
              {"axis": "1", "keepdims": "1"})
    check_fwd("argmin", [x], np.argmin(x, 0), {"axis": "0"})
    check_fwd("argmax_channel", [x], np.argmax(x, 1))


# ---------------------------------------------------------------------------
# broadcast/reshape-like shape ops
# ---------------------------------------------------------------------------

def test_broadcast_shape_ops():
    rng = np.random.RandomState(5)
    x = rng.randn(1, 3, 1).astype(np.float32)
    want = np.broadcast_to(x, (2, 3, 4))
    check_fwd("broadcast_to", [x], want, {"shape": "(2, 0, 4)"})
    for name in ("broadcast_axis", "broadcast_axes"):
        check_fwd(name, [x], want, {"axis": "(0, 2)", "size": "(2, 4)"})
    like = np.zeros((2, 3, 4), np.float32)
    check_fwd("broadcast_like", [x, like], want)
    y = rng.randn(2, 6).astype(np.float32)
    check_fwd("reshape_like", [y, np.zeros((3, 4))], y.reshape(3, 4))


def test_reshape_codes():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    for name in ("Reshape", "reshape"):
        check_fwd(name, [x], x.reshape(4, 6), {"shape": "(4, 6)"})
    check_fwd("reshape", [x], x.reshape(2, 12), {"shape": "(0, -1)"})
    check_fwd("reshape", [x], x.reshape(6, 4), {"shape": "(-3, -2)"})
    check_fwd("reshape", [x], x.reshape(2, 3, 2, 2),
              {"shape": "(0, 0, -4, 2, -1)"})
    check_fwd("reshape", [x], x.reshape(6, 4),
              {"shape": "(-1, 4)"})


def test_flatten():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    for name in ("Flatten", "flatten"):
        check_fwd(name, [x], x.reshape(2, 12))


def test_transpose_swap_expand_squeeze():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 4).astype(np.float32)
    check_fwd("transpose", [x], x.T)
    check_fwd("transpose", [x], np.transpose(x, (1, 0, 2)),
              {"axes": "(1, 0, 2)"})
    for name in ("SwapAxis", "swapaxes"):
        check_fwd(name, [x], np.swapaxes(x, 0, 2),
                  {"dim1": "0", "dim2": "2"})
    check_fwd("expand_dims", [x], x[:, None], {"axis": "1"})
    y = rng.randn(2, 1, 3, 1).astype(np.float32)
    check_fwd("squeeze", [y], np.squeeze(y))
    check_fwd("squeeze", [y], np.squeeze(y, 1), {"axis": "(1,)"})


def test_slice_ops():
    x = np.arange(60, dtype=np.float32).reshape(3, 4, 5)
    for name in ("slice", "crop"):
        check_fwd(name, [x], x[1:3, 0:2],
                  {"begin": "(1, 0)", "end": "(3, 2)"})
    check_fwd("slice", [x], x[0:3:2, :, 1:5:2],
              {"begin": "(0, 0, 1)", "end": "(3, 4, 5)",
               "step": "(2, 1, 2)"})
    check_fwd("slice_axis", [x], x[:, 1:3], {"axis": "1", "begin": "1",
                                             "end": "3"})
    like = np.zeros((2, 2, 5), np.float32)
    check_fwd("slice_like", [x, like], x[:2, :2], {"axes": "(0, 1)"})
    check_grad_fd("slice", [x[:2, :2, 0]], {"begin": "(0, 1)",
                                            "end": "(2, 2)"})


def test_repeat_tile_reverse():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    check_fwd("repeat", [x], np.repeat(x.reshape(-1), 2), {"repeats": "2"})
    check_fwd("repeat", [x], np.repeat(x, 2, axis=1),
              {"repeats": "2", "axis": "1"})
    check_fwd("tile", [x], np.tile(x, (2, 3)), {"reps": "(2, 3)"})
    for name in ("reverse", "flip"):
        check_fwd(name, [x], x[::-1], {"axis": "(0,)"})


def test_concat_stack_split():
    rng = np.random.RandomState(8)
    a, b = rng.randn(2, 3).astype(np.float32), \
        rng.randn(2, 3).astype(np.float32)
    for name in ("Concat", "concat"):
        check_fwd(name, [a, b], np.concatenate([a, b], 1), {"dim": "1"})
    check_fwd("stack", [a, b], np.stack([a, b], 1), {"axis": "1"})
    x = np.arange(24, dtype=np.float32).reshape(2, 6, 2)
    for name in ("SliceChannel", "split"):
        outs = apply_op(name, [x], {"num_outputs": "3", "axis": "1"})
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, x[:, 2 * i:2 * i + 2])
    outs = apply_op("split", [x[:, :3]], {"num_outputs": "3", "axis": "1",
                                          "squeeze_axis": "1"})
    assert outs[0].shape == (2, 2)
    check_grad_fd("Concat", [a, b], {"dim": "0"}, wrt=(0, 1))


def test_pad():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    pw = "(0, 0, 0, 0, 1, 2, 2, 1)"
    pairs = [(0, 0), (0, 0), (1, 2), (2, 1)]
    for name in ("Pad", "pad"):
        check_fwd(name, [x], np.pad(x, pairs, constant_values=3.0),
                  {"pad_width": pw, "mode": "constant",
                   "constant_value": "3"})
    check_fwd("pad", [x], np.pad(x, pairs, mode="edge"),
              {"pad_width": pw, "mode": "edge"})
    check_fwd("pad", [x], np.pad(x, pairs, mode="reflect"),
              {"pad_width": pw, "mode": "reflect"})


def test_space_depth_ops():
    x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
    out = apply_op("space_to_depth", [x], {"block_size": "2"})[0]
    assert out.shape == (1, 8, 2, 2)
    # manual oracle: out[n, c*bs*bs + bi*bs + bj ...] per impl layout
    back = apply_op("depth_to_space", [out], {"block_size": "2"})[0]
    np.testing.assert_array_equal(back, x)  # exact inverses
    # spot-check one known element: block offset (1, 0) of channel 0
    n, c, h, w = x.shape
    s2d = np.asarray(out)
    got = s2d[0, :, 0, 0]
    manual = x.reshape(1, 2, 2, 2, 2, 2).transpose(0, 3, 5, 1, 2, 4)[
        0, :, :, :, 0, 0].reshape(-1)
    np.testing.assert_array_equal(got, manual)


def test_diag():
    x = np.arange(9, dtype=np.float32).reshape(3, 3)
    check_fwd("diag", [x], np.diag(x))
    check_fwd("diag", [x], np.diag(x, 1), {"k": "1"})
    v = np.array([1.0, 2.0], np.float32)
    check_fwd("diag", [v], np.diag(v))


def test_where():
    cond = np.array([[1, 0], [0, 2]], np.float32)
    x = np.ones((2, 2), np.float32)
    y = np.zeros((2, 2), np.float32)
    check_fwd("where", [cond, x, y], np.where(cond != 0, x, y))
    vec = np.array([1, 0], np.float32)
    check_fwd("where", [vec, x, y],
              np.where(vec[:, None] != 0, x, y))


# ---------------------------------------------------------------------------
# dot / batch_dot / L2Normalization
# ---------------------------------------------------------------------------

def test_dot():
    rng = np.random.RandomState(9)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 5).astype(np.float32)
    check_fwd("dot", [a, b], a @ b, rtol=1e-4, atol=1e-4)
    check_fwd("dot", [a.T, b], a @ b, {"transpose_a": "1"},
              rtol=1e-4, atol=1e-4)
    check_fwd("dot", [a, b.T], a @ b, {"transpose_b": "1"},
              rtol=1e-4, atol=1e-4)
    v = rng.randn(4).astype(np.float32)
    check_fwd("dot", [v, v], float(v @ v), rtol=1e-4, atol=1e-4)
    # N-D: reduce last axis of a with first of b
    a3 = rng.randn(2, 3, 4).astype(np.float32)
    b3 = rng.randn(4, 5).astype(np.float32)
    check_fwd("dot", [a3, b3], np.tensordot(a3, b3, axes=([2], [0])),
              rtol=1e-4, atol=1e-4)
    check_grad_fd("dot", [a[:2, :3], b[:3, :2]], wrt=(0, 1))


def test_batch_dot():
    rng = np.random.RandomState(10)
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 4, 5).astype(np.float32)
    check_fwd("batch_dot", [a, b], a @ b, rtol=1e-4, atol=1e-4)
    check_fwd("batch_dot", [np.swapaxes(a, 1, 2), b], a @ b,
              {"transpose_a": "1"}, rtol=1e-4, atol=1e-4)
    check_grad_fd("batch_dot", [a[:, :2, :2], b[:, :2, :2]], wrt=(0, 1))


def test_l2_normalization():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 3, 4).astype(np.float32)
    x64 = x.astype(np.float64)
    eps = 1e-10
    inst = x64 / np.sqrt((x64 ** 2).sum(axis=(1, 2), keepdims=True) + eps)
    check_fwd("L2Normalization", [x], inst, rtol=1e-4, atol=1e-4)
    chan = x64 / np.sqrt((x64 ** 2).sum(axis=1, keepdims=True) + eps)
    check_fwd("L2Normalization", [x], chan, {"mode": "channel"},
              rtol=1e-4, atol=1e-4)
    spat = x64 / np.sqrt((x64 ** 2).sum(axis=2, keepdims=True) + eps)
    check_fwd("L2Normalization", [x], spat, {"mode": "spatial"},
              rtol=1e-4, atol=1e-4)
    check_grad_fd("L2Normalization", [x[:1, :2, :2]])


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

def test_embedding():
    rng = np.random.RandomState(12)
    w = rng.randn(6, 4).astype(np.float32)
    idx = np.array([[0, 2, 5], [1, 1, 3]], np.float32)
    check_fwd("Embedding", [idx, w], w[idx.astype(int)],
              {"input_dim": "6", "output_dim": "4"})
    check_grad_fd("Embedding", [idx, w], {"input_dim": "6",
                                          "output_dim": "4"}, wrt=(1,))


def test_take():
    rng = np.random.RandomState(13)
    a = rng.randn(5, 3).astype(np.float32)
    idx = np.array([[0, 4], [2, 2]], np.float32)
    check_fwd("take", [a, idx], a[idx.astype(int)])
    # clip mode clamps out-of-range
    idx2 = np.array([-1, 7], np.float32)
    check_fwd("take", [a, idx2], a[[0, 4]])
    # wrap mode
    check_fwd("take", [a, idx2], a[[4, 2]], {"mode": "wrap"})
    check_fwd("take", [a, np.array([1.0, 0.0])], a[:, [1, 0]],
              {"axis": "1"})
    check_grad_fd("take", [a[:3, :2], np.array([0.0, 2.0, 1.0])], wrt=(0,))


def test_batch_take():
    a = np.arange(12, dtype=np.float32).reshape(4, 3)
    idx = np.array([0, 2, 1, 0], np.float32)
    check_fwd("batch_take", [a, idx], a[np.arange(4), idx.astype(int)])


def test_one_hot():
    idx = np.array([1, 0, 3], np.float32)
    want = np.eye(4)[idx.astype(int)]
    check_fwd("one_hot", [idx], want, {"depth": "4"})
    want2 = want * (2.0 - 0.5) + 0.5
    check_fwd("one_hot", [idx], want2,
              {"depth": "4", "on_value": "2", "off_value": "0.5"})


def test_gather_scatter_nd():
    data = np.arange(12, dtype=np.float32).reshape(3, 4)
    indices = np.array([[0, 2, 1], [1, 3, 0]], np.float32)
    want = data[[0, 2, 1], [1, 3, 0]]
    check_fwd("gather_nd", [data, indices], want)
    vals = np.array([5.0, 6.0, 7.0], np.float32)
    scattered = np.zeros((3, 4))
    scattered[[0, 2, 1], [1, 3, 0]] = vals
    check_fwd("scatter_nd", [vals, indices], scattered,
              {"shape": "(3, 4)"})
    lhs = np.ones((3, 4), np.float32)
    out = lhs.copy()
    out[[0, 2, 1], [1, 3, 0]] = vals
    check_fwd("_scatter_set_nd", [lhs, vals, indices], out)


def test_pick():
    rng = np.random.RandomState(14)
    data = rng.randn(3, 4).astype(np.float32)
    idx = np.array([0, 3, 1], np.float32)
    check_fwd("pick", [data, idx], data[np.arange(3), idx.astype(int)])
    check_fwd("pick", [data, idx],
              data[np.arange(3), idx.astype(int)][:, None],
              {"keepdims": "1"})
    idx0 = np.array([0, 2, 1, 0], np.float32)
    check_fwd("pick", [data, idx0], data[idx0.astype(int),
                                         np.arange(4)], {"axis": "0"})


# ---------------------------------------------------------------------------
# ordering
# ---------------------------------------------------------------------------

def test_sort_argsort():
    rng = np.random.RandomState(15)
    x = rng.randn(3, 5).astype(np.float32)
    check_fwd("sort", [x], np.sort(x, -1))
    check_fwd("sort", [x], -np.sort(-x, 0), {"axis": "0",
                                             "is_ascend": "0"})
    check_fwd("sort", [x], np.sort(x.reshape(-1)), {"axis": "None"})
    check_fwd("argsort", [x], np.argsort(x, -1))
    check_fwd("argsort", [x], np.argsort(-x, 1), {"is_ascend": "0"})


def test_topk():
    rng = np.random.RandomState(16)
    x = rng.randn(3, 6).astype(np.float32)
    ord_idx = np.argsort(-x, axis=1)[:, :2]
    vals = np.take_along_axis(x, ord_idx, 1)
    check_fwd("topk", [x], ord_idx, {"k": "2"})
    check_fwd("topk", [x], vals, {"k": "2", "ret_typ": "value"})
    outs = apply_op("topk", [x], {"k": "2", "ret_typ": "both"})
    np.testing.assert_allclose(outs[0], vals, rtol=1e-6)
    np.testing.assert_array_equal(outs[1], ord_idx)
    mask = apply_op("topk", [x], {"k": "2", "ret_typ": "mask"})[0]
    manual = np.zeros_like(x)
    np.put_along_axis(manual, ord_idx, 1.0, 1)
    np.testing.assert_array_equal(mask, manual)
    # ascending = smallest-k
    asc_idx = np.argsort(x, axis=1)[:, :2]
    check_fwd("topk", [x], np.take_along_axis(x, asc_idx, 1),
              {"k": "2", "ret_typ": "value", "is_ascend": "1"})


# ---------------------------------------------------------------------------
# init ops
# ---------------------------------------------------------------------------

def test_init_ops():
    for name in ("_zeros", "zeros"):
        out = apply_op(name, [], {"shape": "(2, 3)"})[0]
        np.testing.assert_array_equal(out, np.zeros((2, 3)))
    for name in ("_ones", "ones"):
        out = apply_op(name, [], {"shape": "(2, 3)", "dtype": "int32"})[0]
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, np.ones((2, 3)))
    for name in ("_full", "full"):
        check_fwd(name, [], np.full((2, 2), 3.5),
                  {"shape": "(2, 2)", "value": "3.5"})
    for name in ("_arange", "arange"):
        check_fwd(name, [], np.arange(2, 8, 2, dtype=np.float32),
                  {"start": "2", "stop": "8", "step": "2"})
    check_fwd("arange", [], np.arange(5, dtype=np.float32),
              {"start": "5"})
    check_fwd("arange", [], np.repeat(np.arange(3), 2),
              {"start": "0", "stop": "3", "repeat": "2"})
    for name in ("_eye", "eye"):
        check_fwd(name, [], np.eye(3, 4, k=1), {"N": "3", "M": "4",
                                                "k": "1"})


# ---------------------------------------------------------------------------
# linalg octet
# ---------------------------------------------------------------------------

def _spd(n, seed):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


def test_linalg_gemm():
    rng = np.random.RandomState(17)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 5).astype(np.float32)
    c = rng.randn(3, 5).astype(np.float32)
    for name in ("_linalg_gemm", "linalg_gemm"):
        check_fwd(name, [a, b, c], 2.0 * a @ b + 3.0 * c,
                  {"alpha": "2", "beta": "3"}, rtol=1e-4, atol=1e-4)
    check_fwd("linalg_gemm", [a.T, b, c], a @ b + c,
              {"transpose_a": "1"}, rtol=1e-4, atol=1e-4)
    for name in ("_linalg_gemm2", "linalg_gemm2"):
        check_fwd(name, [a, b], 2.0 * a @ b, {"alpha": "2"},
                  rtol=1e-4, atol=1e-4)
    check_grad_fd("linalg_gemm2", [a[:2, :3], b[:3, :2]], wrt=(0, 1))


def test_linalg_potrf_potri():
    a = _spd(4, 18)
    l = np.linalg.cholesky(a.astype(np.float64))
    for name in ("_linalg_potrf", "linalg_potrf"):
        check_fwd(name, [a], l, rtol=1e-3, atol=1e-3)
    for name in ("_linalg_potri", "linalg_potri"):
        check_fwd(name, [l.astype(np.float32)],
                  np.linalg.inv(a.astype(np.float64)),
                  rtol=1e-2, atol=1e-3)


def test_linalg_trmm_trsm():
    rng = np.random.RandomState(19)
    l = np.tril(rng.randn(3, 3) + 3 * np.eye(3)).astype(np.float32)
    b = rng.randn(3, 4).astype(np.float32)
    for name in ("_linalg_trmm", "linalg_trmm"):
        check_fwd(name, [l, b], 2.0 * l.astype(np.float64) @ b,
                  {"alpha": "2"}, rtol=1e-4, atol=1e-4)
    check_fwd("linalg_trmm", [l, b], l.T.astype(np.float64) @ b,
              {"transpose": "1"}, rtol=1e-4, atol=1e-4)
    br = rng.randn(4, 3).astype(np.float32)
    check_fwd("linalg_trmm", [l, br], br.astype(np.float64) @ l,
              {"rightside": "1"}, rtol=1e-4, atol=1e-4)
    for name in ("_linalg_trsm", "linalg_trsm"):
        want = np.linalg.solve(l.astype(np.float64), b)
        check_fwd(name, [l, b], want, rtol=1e-3, atol=1e-3)
    check_fwd("linalg_trsm", [l, b],
              np.linalg.solve(l.T.astype(np.float64), b),
              {"transpose": "1"}, rtol=1e-3, atol=1e-3)
    check_fwd("linalg_trsm", [l, br],
              br.astype(np.float64) @ np.linalg.inv(l.astype(np.float64)),
              {"rightside": "1"}, rtol=1e-3, atol=1e-3)


def test_linalg_syrk_sumlogdiag_gelqf():
    rng = np.random.RandomState(20)
    a = rng.randn(3, 4).astype(np.float32)
    for name in ("_linalg_syrk", "linalg_syrk"):
        check_fwd(name, [a], a.astype(np.float64) @ a.T,
                  rtol=1e-4, atol=1e-4)
    check_fwd("linalg_syrk", [a], a.T.astype(np.float64) @ a,
              {"transpose": "1"}, rtol=1e-4, atol=1e-4)
    spd = _spd(3, 21)
    l = np.linalg.cholesky(spd.astype(np.float64)).astype(np.float32)
    for name in ("_linalg_sumlogdiag", "linalg_sumlogdiag"):
        check_fwd(name, [l], np.log(np.diag(l)).sum(),
                  rtol=1e-4, atol=1e-4)
    # LQ: A = L @ Q, Q row-orthonormal, L lower-triangular
    a2 = rng.randn(3, 5).astype(np.float32)
    for name in ("_linalg_gelqf", "linalg_gelqf"):
        lq = apply_op(name, [a2])
        lm, q = lq[0].astype(np.float64), lq[1].astype(np.float64)
        np.testing.assert_allclose(lm @ q, a2, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(q @ q.T, np.eye(3), rtol=1e-4,
                                   atol=1e-4)
        assert np.allclose(lm, np.tril(lm), atol=1e-5)


# ---------------------------------------------------------------------------
# optimizer update ops (reference update math incl. rescale/clip/wd)
# ---------------------------------------------------------------------------

def _np_prep_grad(g, w, rescale=1.0, clip=-1.0, wd=0.0):
    g = g * rescale
    if clip > 0:
        g = np.clip(g, -clip, clip)
    return g + wd * w


OPT_ATTRS = {"lr": "0.1", "rescale_grad": "0.5", "clip_gradient": "1.0",
             "wd": "0.01"}


def _opt_inputs(n=6, seed=22):
    rng = np.random.RandomState(seed)
    return (rng.randn(n).astype(np.float32),
            (rng.randn(n) * 4).astype(np.float32))  # big grads hit clip


def test_sgd_update():
    w, g = _opt_inputs()
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    check_fwd("sgd_update", [w, g], w - 0.1 * gp, OPT_ATTRS,
              rtol=1e-5, atol=1e-5)


def test_sgd_mom_update():
    w, g = _opt_inputs()
    mom = np.ones_like(w) * 0.2
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    new_mom = 0.9 * mom - 0.1 * gp
    attrs = dict(OPT_ATTRS, momentum="0.9")
    check_fwd("sgd_mom_update", [w, g, mom], [w + new_mom, new_mom],
              attrs, rtol=1e-5, atol=1e-5)


def test_nag_mom_update():
    w, g = _opt_inputs()
    mom = np.ones_like(w) * 0.2
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    new_mom = 0.9 * mom + gp
    want_w = w - 0.1 * (gp + 0.9 * new_mom)
    check_fwd("nag_mom_update", [w, g, mom], [want_w, new_mom],
              dict(OPT_ATTRS, momentum="0.9"), rtol=1e-5, atol=1e-5)


def test_adam_update():
    w, g = _opt_inputs()
    mean = np.full_like(w, 0.1)
    var = np.full_like(w, 0.2)
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    nm = 0.9 * mean + 0.1 * gp
    nv = 0.999 * var + 0.001 * gp ** 2
    nw = w - 0.1 * nm / (np.sqrt(nv) + 1e-8)
    check_fwd("adam_update", [w, g, mean, var], [nw, nm, nv],
              OPT_ATTRS, rtol=1e-5, atol=1e-5)


def test_rmsprop_update():
    w, g = _opt_inputs()
    n = np.full_like(w, 0.3)
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    nn = 0.05 * gp ** 2 + 0.95 * n
    nw = w - 0.1 * gp / np.sqrt(nn + 1e-8)
    check_fwd("rmsprop_update", [w, g, n], [nw, nn], OPT_ATTRS,
              rtol=1e-5, atol=1e-5)


def test_rmspropalex_update():
    w, g = _opt_inputs()
    n = np.full_like(w, 0.3)
    gbar = np.full_like(w, 0.05)
    delta = np.full_like(w, -0.02)
    gp = _np_prep_grad(g.astype(np.float64), w, 0.5, 1.0, 0.01)
    nn = 0.05 * gp ** 2 + 0.95 * n
    ng = 0.05 * gp + 0.95 * gbar
    nd = 0.9 * delta - 0.1 * gp / np.sqrt(nn - ng ** 2 + 1e-8)
    check_fwd("rmspropalex_update", [w, g, n, gbar, delta],
              [w + nd, nn, ng, nd], OPT_ATTRS, rtol=1e-5, atol=1e-5)


def test_ftrl_update():
    w, g = _opt_inputs()
    z = np.full_like(w, 0.1)
    n = np.full_like(w, 0.2)
    g64 = g.astype(np.float64) * 0.5
    g64 = np.clip(g64, -1.0, 1.0)
    lr, lamda1, beta, wd = 0.1, 0.01, 1.0, 0.01
    nz = z + g64 - (np.sqrt(n + g64 ** 2) - np.sqrt(n)) / lr * w
    nn = n + g64 ** 2
    nw = (np.sign(nz) * lamda1 - nz) / ((beta + np.sqrt(nn)) / lr + wd) \
        * (np.abs(nz) > lamda1)
    check_fwd("ftrl_update", [w, g, z, n], [nw, nz, nn],
              dict(OPT_ATTRS, lamda1="0.01", beta="1.0"),
              rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# random / sample ops — statistical numeric asserts, fixed keys
# ---------------------------------------------------------------------------

N_STAT = 20000


def _stat(name, attrs, mean, std, lo=None, hi=None, seed=0):
    out = apply_op(name, [], dict(attrs, shape="(%d,)" % N_STAT),
                   seed=seed)[0].astype(np.float64)
    assert out.shape == (N_STAT,)
    tol = 5 * std / np.sqrt(N_STAT) + 1e-3
    assert abs(out.mean() - mean) < tol, (name, out.mean(), mean, tol)
    if lo is not None:
        assert out.min() >= lo, name
    if hi is not None:
        assert out.max() <= hi, name
    # determinism under the same key
    out2 = apply_op(name, [], dict(attrs, shape="(%d,)" % N_STAT),
                    seed=seed)[0]
    np.testing.assert_array_equal(out, out2)


def test_random_uniform():
    for name in ("_random_uniform", "uniform", "random_uniform"):
        _stat(name, {"low": "2", "high": "4"}, 3.0,
              (4 - 2) / np.sqrt(12), lo=2, hi=4)


def test_random_normal():
    for name in ("_random_normal", "normal", "random_normal"):
        _stat(name, {"loc": "1.5", "scale": "2"}, 1.5, 2.0)


def test_random_gamma():
    for name in ("_random_gamma", "random_gamma"):
        _stat(name, {"alpha": "3", "beta": "2"}, 6.0,
              np.sqrt(3) * 2, lo=0)


def test_random_exponential():
    for name in ("_random_exponential", "random_exponential"):
        _stat(name, {"lam": "4"}, 0.25, 0.25, lo=0)


def test_random_poisson():
    for name in ("_random_poisson", "random_poisson"):
        _stat(name, {"lam": "3"}, 3.0, np.sqrt(3), lo=0)


def test_random_negative_binomial():
    k, p = 4, 0.4
    for name in ("_random_negative_binomial", "random_negative_binomial"):
        _stat(name, {"k": str(k), "p": str(p)}, k * (1 - p) / p,
              np.sqrt(k * (1 - p)) / p, lo=0)


def test_random_generalized_negative_binomial():
    mu, alpha = 2.0, 0.5
    var = mu + alpha * mu * mu
    for name in ("_random_generalized_negative_binomial",
                 "random_generalized_negative_binomial"):
        _stat(name, {"mu": str(mu), "alpha": str(alpha)}, mu,
              np.sqrt(var), lo=0)


def test_sample_ops():
    low = np.array([0.0, 10.0], np.float32)
    high = np.array([1.0, 11.0], np.float32)
    out = apply_op("sample_uniform", [low, high],
                   {"shape": "(500,)"})[0]
    assert out.shape == (2, 500)
    assert (out[0] >= 0).all() and (out[0] <= 1).all()
    assert (out[1] >= 10).all() and (out[1] <= 11).all()

    mu = np.array([0.0, 5.0], np.float32)
    sd = np.array([1.0, 0.1], np.float32)
    out = apply_op("sample_normal", [mu, sd], {"shape": "(2000,)"})[0]
    assert abs(out[0].mean()) < 0.2 and abs(out[1].mean() - 5.0) < 0.05
    assert abs(out[1].std() - 0.1) < 0.05

    alpha = np.array([2.0, 8.0], np.float32)
    beta = np.array([1.0, 0.5], np.float32)
    out = apply_op("sample_gamma", [alpha, beta],
                   {"shape": "(3000,)"})[0].astype(np.float64)
    np.testing.assert_allclose(out.mean(axis=1), alpha * beta, rtol=0.2)

    lam = np.array([1.0, 5.0], np.float32)
    out = apply_op("sample_exponential", [lam],
                   {"shape": "(3000,)"})[0].astype(np.float64)
    np.testing.assert_allclose(out.mean(axis=1), 1.0 / lam, rtol=0.2)

    out = apply_op("sample_poisson", [lam],
                   {"shape": "(3000,)"})[0].astype(np.float64)
    np.testing.assert_allclose(out.mean(axis=1), lam, rtol=0.2)


def test_multinomial():
    p = np.array([[0.1, 0.6, 0.3], [0.8, 0.1, 0.1]], np.float32)
    for name in ("_sample_multinomial", "sample_multinomial"):
        out = apply_op(name, [p], {"shape": "(4000,)"})[0]
        assert out.shape == (2, 4000)
        for row in range(2):
            freq = np.bincount(out[row].astype(int), minlength=3) / 4000.0
            np.testing.assert_allclose(freq, p[row], atol=0.05)
    flat = apply_op("sample_multinomial", [p[0]], {"shape": "(4000,)"})[0]
    freq = np.bincount(flat.astype(int), minlength=3) / 4000.0
    np.testing.assert_allclose(freq, p[0], atol=0.05)


def test_shuffle():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    for name in ("_shuffle", "shuffle"):
        out = apply_op(name, [x], seed=3)[0]
        # rows preserved as units, full multiset preserved
        np.testing.assert_array_equal(
            np.sort(out[:, 0]), x[:, 0])
        np.testing.assert_array_equal(out[:, 1] - out[:, 0],
                                      np.ones(10))


# ---------------------------------------------------------------------------
# coverage ledger: ops exercised by the named tests above (consumed by
# test_operator_nn.test_all_ops_covered)
# ---------------------------------------------------------------------------

EXTRA_COVERED = {
    "BlockGrad", "stop_gradient", "make_loss", "MakeLoss", "Cast", "cast",
    "clip", "smooth_l1", "add_n", "ElementWiseSum", "_sum", "norm",
    "argmax", "argmin", "argmax_channel", "broadcast_to", "broadcast_axis",
    "broadcast_axes", "broadcast_like", "reshape_like", "Reshape",
    "reshape", "Flatten", "flatten", "transpose", "SwapAxis", "swapaxes",
    "expand_dims", "squeeze", "slice", "crop", "slice_axis", "slice_like",
    "repeat", "tile", "reverse", "flip", "Concat", "concat", "stack",
    "SliceChannel", "split", "Pad", "pad", "space_to_depth",
    "depth_to_space", "diag", "where", "dot", "batch_dot",
    "L2Normalization", "Embedding", "take", "batch_take", "one_hot",
    "gather_nd", "scatter_nd", "_scatter_set_nd", "pick", "sort",
    "argsort", "topk", "_zeros", "zeros", "_ones", "ones", "_full",
    "full", "_arange", "arange", "_eye", "eye",
    "_linalg_gemm", "linalg_gemm", "_linalg_gemm2", "linalg_gemm2",
    "_linalg_potrf", "linalg_potrf", "_linalg_potri", "linalg_potri",
    "_linalg_trmm", "linalg_trmm", "_linalg_trsm", "linalg_trsm",
    "_linalg_syrk", "linalg_syrk", "_linalg_sumlogdiag",
    "linalg_sumlogdiag", "_linalg_gelqf", "linalg_gelqf",
    "sgd_update", "sgd_mom_update", "nag_mom_update", "adam_update",
    "rmsprop_update", "rmspropalex_update", "ftrl_update",
    "_random_uniform", "uniform", "random_uniform", "_random_normal",
    "normal", "random_normal", "_random_gamma", "random_gamma",
    "_random_exponential", "random_exponential", "_random_poisson",
    "random_poisson", "_random_negative_binomial",
    "random_negative_binomial", "_random_generalized_negative_binomial",
    "random_generalized_negative_binomial", "sample_uniform",
    "sample_normal", "sample_gamma", "sample_exponential",
    "sample_poisson", "_sample_multinomial", "sample_multinomial",
    "_shuffle", "shuffle",
}


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg (identity_attach_KL_sparse_reg-inl.h)
# ---------------------------------------------------------------------------


def test_identity_attach_kl_sparse_reg_forward_and_aux():
    rng = np.random.RandomState(4)
    x = rng.uniform(0.05, 0.95, (6, 5)).astype(np.float32)
    avg = np.full(5, 0.3, np.float32)
    op = get_op("IdentityAttachKLSparseReg")
    outs, aux = op.apply([jnp.asarray(x), jnp.asarray(avg)],
                         {"momentum": "0.9"}, OpContext(is_train=True))
    np.testing.assert_allclose(np.asarray(outs[0]), x)  # identity fwd
    np.testing.assert_allclose(np.asarray(aux[0]),
                               0.9 * avg + 0.1 * x.mean(0), rtol=1e-6)
    # inference: identity, aux untouched
    outs, aux = op.apply([jnp.asarray(x), jnp.asarray(avg)], {},
                         OpContext(is_train=False))
    np.testing.assert_allclose(np.asarray(outs[0]), x)
    np.testing.assert_allclose(np.asarray(aux[0]), avg)


def test_identity_attach_kl_sparse_reg_grad_finite_diff():
    """With momentum=0 the attached term is the exact gradient of
    J(x) = Σ(ct·x) + penalty · B · Σ_j KL(t ‖ colmean_j(x))."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd as ag

    rng = np.random.RandomState(11)
    B, H = 8, 4
    t, penalty = 0.2, 0.05
    x = rng.uniform(0.1, 0.9, (B, H)).astype(np.float32)
    ct = rng.randn(B, H).astype(np.float32)

    def objective(xv):
        a = xv.mean(0)
        kl = t * np.log(t / a) + (1 - t) * np.log((1 - t) / (1 - a))
        return float((ct * xv).sum() + penalty * B * kl.sum())

    x_nd = mx.nd.array(x)
    x_nd.attach_grad()
    avg_nd = mx.nd.array(np.full(H, 0.5, np.float32))
    with ag.record():
        out = mx.nd.IdentityAttachKLSparseReg(
            x_nd, avg_nd, sparseness_target=t, penalty=penalty,
            momentum=0.0)
        loss = mx.nd.sum(out * mx.nd.array(ct))
    loss.backward()
    g = x_nd.grad.asnumpy()

    eps = 1e-3
    for i, j in [(0, 0), (3, 1), (7, 3)]:
        xp, xm = x.astype(np.float64), x.astype(np.float64)
        xp, xm = xp.copy(), xm.copy()
        xp[i, j] += eps
        xm[i, j] -= eps
        fd = (objective(xp) - objective(xm)) / (2 * eps)
        np.testing.assert_allclose(g[i, j], fd, rtol=2e-3, atol=1e-5)
