"""Interop with GENUINE reference (MXNet v0.11-era) artifacts.

Fixtures: ``tests/fixtures/save_000800.json`` is vendored VERBATIM from
the reference test suite (``tests/python/unittest/save_000800.json`` —
a pre-0.9 symbol file, old ``param``/``attr`` schema, 2-tuple heads);
the ``.params`` bytes are hand-assembled in this file to the exact
binary layout of ``src/ndarray/ndarray.cc:668-744`` (u64 list magic +
reserved, per-array V1 shape magic / legacy ndim framing, Context,
mshadow type flag, raw data, dmlc string vector of names) — what a real
``mx.nd.save`` of that era produced.
"""
import os
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE_JSON = os.path.join(HERE, "fixtures", "save_000800.json")


def _genuine_params_bytes(named_arrays, legacy_shape=False):
    """Assemble bytes exactly as the reference NDArray::Save wrote them
    (ndarray.cc:668-691; legacy_shape uses the pre-0.9 framing where
    the magic word IS ndim, LegacyTShapeLoad ndarray.cc:693-709)."""
    out = struct.pack("<QQ", 0x112, 0)           # list magic, reserved
    out += struct.pack("<Q", len(named_arrays))
    for _, a in named_arrays:
        a = np.ascontiguousarray(a)
        if legacy_shape:
            out += struct.pack("<I", a.ndim)
            out += struct.pack("<%dI" % a.ndim, *a.shape)
        else:
            out += struct.pack("<I", 0xF993FAC8)  # NDARRAY_V1_MAGIC
            out += struct.pack("<I", a.ndim)
            out += struct.pack("<%dq" % a.ndim, *a.shape)
        out += struct.pack("<ii", 1, 0)           # Context kCPU dev0
        flags = {"float32": 0, "float64": 1, "uint8": 3, "int32": 4}
        out += struct.pack("<i", flags[a.dtype.name])
        out += a.tobytes()
    out += struct.pack("<Q", len(named_arrays))
    for name, _ in named_arrays:
        nb = name.encode()
        out += struct.pack("<Q", len(nb)) + nb
    return out


def test_load_genuine_symbol_json_and_forward():
    """The vendored pre-0.9 reference symbol loads, keeps its ctx_group
    annotation attrs, binds, and runs forward."""
    net = mx.sym.load(FIXTURE_JSON)
    args = net.list_arguments()
    assert args[0] == "data" and "fc1_weight" in args
    # annotation attrs from the legacy "attr" field survive
    assert net.attr_dict()["fc1"]["ctx_group"] == "stage1"
    ex = net.simple_bind(data=(2, 10), softmax_label=(2,))
    ex.arg_dict["data"][:] = mx.nd.array(
        np.random.RandomState(0).randn(2, 10).astype(np.float32))
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape[0] == 2
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


@pytest.mark.parametrize("legacy_shape", [False, True])
def test_load_genuine_params_binary(tmp_path, legacy_shape):
    """Hand-assembled reference-layout .params bytes load through
    mx.nd.load — both the 0.9+ V1 shape framing and the pre-0.9
    legacy (magic = ndim) framing."""
    rng = np.random.RandomState(1)
    named = [("arg:fc1_weight", rng.randn(128, 10).astype(np.float32)),
             ("arg:fc1_bias", rng.randn(128).astype(np.float32)),
             ("aux:counter", np.arange(4, dtype=np.int32))]
    p = str(tmp_path / "legacy.params")
    open(p, "wb").write(_genuine_params_bytes(named,
                                              legacy_shape=legacy_shape))
    loaded = mx.nd.load(p)
    assert set(loaded) == {n for n, _ in named}
    for n, a in named:
        np.testing.assert_array_equal(loaded[n].asnumpy(), a)
        assert loaded[n].dtype == a.dtype


def test_genuine_checkpoint_pair_roundtrip(tmp_path):
    """The full reference two-file contract: vendored symbol JSON +
    reference-layout .params with arg:/aux: prefixes feed
    model.load_checkpoint-style consumption AND our saver emits bytes
    the reference loader semantics accept (our own load reads them via
    the reference branch, not the legacy-own branch)."""
    net = mx.sym.load(FIXTURE_JSON)
    rng = np.random.RandomState(2)
    shapes, _, _ = net.infer_shape(data=(2, 10), softmax_label=(2,))
    named = []
    for n, s in zip(net.list_arguments(), shapes):
        if n in ("data", "softmax_label"):
            continue
        named.append(("arg:%s" % n,
                      rng.randn(*s).astype(np.float32) * 0.1))
    p = str(tmp_path / "model-0000.params")
    open(p, "wb").write(_genuine_params_bytes(named))
    params = mx.nd.load(p)
    arg_params = {k[4:]: v for k, v in params.items()
                  if k.startswith("arg:")}

    ex = net.simple_bind(data=(2, 10), softmax_label=(2,))
    for n, v in arg_params.items():
        ex.arg_dict[n][:] = v
    ex.arg_dict["data"][:] = mx.nd.array(
        rng.randn(2, 10).astype(np.float32))
    out1 = ex.forward(is_train=False)[0].asnumpy()

    # round-trip through OUR saver: the bytes must parse down the
    # reference branch (reserved word 0), not the own-format branch
    p2 = str(tmp_path / "resaved.params")
    mx.nd.save(p2, {k: mx.nd.array(v) for k, v in params.items()})
    raw = open(p2, "rb").read()
    magic, reserved = struct.unpack("<QQ", raw[:16])
    assert (magic, reserved) == (0x112, 0)
    (v1magic,) = struct.unpack("<I", raw[24:28])
    assert v1magic == 0xF993FAC8
    again = mx.nd.load(p2)
    for k in params:
        np.testing.assert_array_equal(again[k].asnumpy(),
                                      params[k].asnumpy())
    # same forward from the re-saved checkpoint
    for n, v in arg_params.items():
        ex.arg_dict[n][:] = again["arg:%s" % n]
    out2 = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(out1, out2)


def test_legacy_batchnorm_json_synthesizes_aux():
    """Pre-0.9 JSON omits aux-state inputs: a BatchNorm node with only
    (data, gamma, beta) inputs gains <name>_moving_mean/var variables
    on load (UpgradeJSON_000800_000900 parity)."""
    import json

    legacy = {
        "nodes": [
            {"op": "null", "param": {}, "name": "data", "inputs": [],
             "backward_source_id": -1},
            {"op": "null", "param": {}, "name": "bn_gamma",
             "inputs": [], "backward_source_id": -1},
            {"op": "null", "param": {}, "name": "bn_beta",
             "inputs": [], "backward_source_id": -1},
            {"op": "BatchNorm", "param": {"fix_gamma": "False"},
             "name": "bn",
             "inputs": [[0, 0], [1, 0], [2, 0]],
             "backward_source_id": -1},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0]],
    }
    net = mx.sym.load_json(json.dumps(legacy))
    assert net.list_auxiliary_states() == ["bn_moving_mean",
                                           "bn_moving_var"]
    ex = net.simple_bind(data=(2, 3, 4, 4))
    x = np.random.RandomState(3).randn(2, 3, 4, 4).astype(np.float32)
    ex.arg_dict["data"][:] = mx.nd.array(x)
    ex.arg_dict["bn_gamma"][:] = mx.nd.array(np.ones(3, np.float32))
    ex.arg_dict["bn_beta"][:] = mx.nd.array(np.zeros(3, np.float32))
    ex.forward(is_train=True)  # training forward defers; read outputs
    out = ex.outputs[0].asnumpy()
    ref = (x - x.mean((0, 2, 3), keepdims=True)) / np.sqrt(
        x.var((0, 2, 3), keepdims=True) + 1e-3)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_argmax_legacy_axis_sentinel():
    """argmax with the pre-0.9.5 axis='-1' sentinel upgrades to
    axis-dropped (flatten-all) semantics."""
    import json

    legacy = {
        "nodes": [
            {"op": "null", "param": {}, "name": "data", "inputs": [],
             "backward_source_id": -1},
            {"op": "argmax", "param": {"axis": "-1"}, "name": "am",
             "inputs": [[0, 0]], "backward_source_id": -1},
        ],
        "arg_nodes": [0],
        "heads": [[1, 0]],
    }
    net = mx.sym.load_json(json.dumps(legacy))
    assert "axis" not in net.attr_dict().get("am", {})
