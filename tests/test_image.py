"""mx.image / ImageRecordIter / im2rec tests — reference
``tests/python/unittest/test_image.py`` + the io pipeline philosophy
(synthetic images, full pack→iterate roundtrip)."""
import os
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio

cv2 = pytest.importorskip("cv2")


def _synth_image(rng, h=40, w=48):
    img = np.zeros((h, w, 3), np.uint8)
    img[:] = rng.randint(0, 255, (h, w, 3))
    return img


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    """Class-per-subdir layout of synthetic JPEGs."""
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for cls in ["cat", "dog"]:
        d = root / cls
        d.mkdir()
        for i in range(6):
            img = _synth_image(rng)
            cv2.imwrite(str(d / ("%s_%d.jpg" % (cls, i))), img)
    return str(root)


def test_imdecode_imresize_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    img = _synth_image(rng)
    ok, buf = cv2.imencode(".png", img)  # png is lossless
    decoded = mx.image.imdecode(buf.tobytes())
    # imdecode returns RGB; cv2 wrote BGR
    np.testing.assert_array_equal(decoded.asnumpy(), img[:, :, ::-1])
    resized = mx.image.imresize(decoded, 24, 20)
    assert resized.shape == (20, 24, 3)


def test_crop_and_resize_helpers():
    rng = np.random.RandomState(2)
    src = mx.nd.array(_synth_image(rng, 40, 48))
    out = mx.image.resize_short(src, 32)
    assert min(out.shape[:2]) == 32
    cropped, (x0, y0, w, h) = mx.image.center_crop(src, (24, 24))
    assert cropped.shape == (24, 24, 3)
    cropped2, _ = mx.image.random_crop(src, (16, 16))
    assert cropped2.shape == (16, 16, 3)
    fixed = mx.image.fixed_crop(src, 2, 3, 10, 12)
    np.testing.assert_array_equal(fixed.asnumpy(),
                                  src.asnumpy()[3:15, 2:12])


def test_color_normalize_and_augmenters():
    rng = np.random.RandomState(3)
    src = mx.nd.array(_synth_image(rng).astype(np.float32))
    normed = mx.image.color_normalize(src, np.array([1.0, 2.0, 3.0]),
                                      np.array([2.0, 2.0, 2.0]))
    expect = (src.asnumpy() - [1, 2, 3]) / [2, 2, 2]
    np.testing.assert_allclose(normed.asnumpy(), expect, rtol=1e-5)

    auglist = mx.image.CreateAugmenter((3, 24, 24), rand_mirror=True,
                                       brightness=0.1, contrast=0.1,
                                       saturation=0.1, hue=0.1,
                                       pca_noise=0.1, rand_gray=0.2,
                                       mean=True, std=True)
    data = [src]
    for aug in auglist:
        data = [r for s in data for r in aug(s)]
    assert data[0].shape == (24, 24, 3)


def test_im2rec_pack_and_image_record_iter(image_dir, tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import im2rec

    prefix = str(tmp_path / "pack")
    im2rec.main([prefix, image_dir, "--list"])
    assert os.path.isfile(prefix + ".lst")
    im2rec.main([prefix, image_dir])
    assert os.path.isfile(prefix + ".rec")
    assert os.path.isfile(prefix + ".idx")

    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 32, 32), batch_size=4, shuffle=True,
        rand_mirror=True, mean_r=128, mean_g=128, mean_b=128,
        preprocess_threads=2)
    nbatch = 0
    labels = set()
    for batch in it:
        assert batch.data[0].shape == (4, 3, 32, 32)
        labels.update(batch.label[0].asnumpy().tolist())
        nbatch += 1
    assert nbatch == 3  # 12 images / 4
    assert labels == {0.0, 1.0}
    # reset + re-iterate works (prefetch thread restart)
    it.reset()
    assert sum(1 for _ in it) == 3


def test_image_record_uint8_iter(image_dir, tmp_path):
    """uint8 transport (reference ImageRecordUInt8Iter,
    iter_image_recordio_2.cc:612): batches stay uint8; normalization is
    the device's job."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import im2rec

    prefix = str(tmp_path / "packu8")
    im2rec.main([prefix, image_dir])
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 32, 32), batch_size=4, dtype="uint8",
        rand_crop=True, rand_mirror=True, preprocess_threads=2)
    batch = next(it)
    arr = batch.data[0].asnumpy()
    assert arr.dtype == np.uint8 and arr.shape == (4, 3, 32, 32)
    assert it.provide_data[0].dtype == np.uint8
    assert arr.max() > 0  # decoded real pixels, not zeros
    with pytest.raises(mx.base.MXNetError):
        mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=4, dtype="uint8", mean_r=128)


def test_image_iter_from_imglist(image_dir):
    files = []
    for cls_i, cls in enumerate(sorted(os.listdir(image_dir))):
        for f in sorted(os.listdir(os.path.join(image_dir, cls))):
            files.append([cls_i, os.path.join(cls, f)])
    it = mx.image.ImageIter(batch_size=3, data_shape=(3, 28, 28),
                            imglist=files, path_root=image_dir,
                            shuffle=False)
    batch = next(it)
    assert batch.data[0].shape == (3, 3, 28, 28)
    assert batch.label[0].shape == (3,)
