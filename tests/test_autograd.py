"""Autograd tests — reference ``tests/python/unittest/test_autograd.py``
semantics: tape-recorded imperative ops, mark_variables, grad vs analytic."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = x * x + 2 * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy() + 2,
                               rtol=1e-5)


def test_chain_grad():
    x = mx.nd.array(np.random.rand(3, 4).astype(np.float32) + 0.5)
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(mx.nd.log(x) * 2.0)  # = x^2
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-4)


def test_dot_grad():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(4, 5).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = mx.nd.dot(a, b)
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(),
                               np.ones((3, 5)).dot(b_np.T), rtol=1e-5)
    np.testing.assert_allclose(b.grad.asnumpy(),
                               a_np.T.dot(np.ones((3, 5))), rtol=1e-5)


def test_head_grad():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
    y.backward(mx.nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_add_req():
    x = mx.nd.array([2.0])
    g = mx.nd.zeros((1,))
    ag.mark_variables([x], [g], "add")
    for _ in range(3):
        with ag.record():
            y = x * x
        y.backward()
    np.testing.assert_allclose(g.asnumpy(), [12.0])  # 3 * 2x


def test_pause_and_modes():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
            z = x * 5  # not recorded
        y = x * 2
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_mul_constant_branches():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * x * x  # x^3 → 3x^2 = 27
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [27.0], rtol=1e-5)


def test_grad_function():
    x = mx.nd.array([2.0, 3.0])
    with ag.record():
        y = mx.nd.sum(x * x)
    # autograd.grad API (returns grads without attach)
    gx = ag.grad(y, [x])[0]
    np.testing.assert_allclose(gx.asnumpy(), 2 * x.asnumpy())


def test_softmax_output_loss_grad():
    # SoftmaxOutput backward = (p - onehot) ignoring out-grad
    data = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    label = mx.nd.array(np.array([0, 1, 2, 3], dtype=np.float32))
    data.attach_grad()
    with ag.record():
        out = mx.nd.SoftmaxOutput(data, label)
    out.backward()
    p = out.asnumpy()
    oh = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    np.testing.assert_allclose(data.grad.asnumpy(), p - oh, rtol=1e-5,
                               atol=1e-6)


def test_dropout_train_vs_predict():
    x = mx.nd.ones((100, 100))
    with ag.record(train_mode=True):
        y = mx.nd.Dropout(x, p=0.5)
    frac_zero = (y.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # predict mode: identity
    y2 = mx.nd.Dropout(x, p=0.5)
    np.testing.assert_allclose(y2.asnumpy(), x.asnumpy())


def test_batchnorm_imperative_aux_update():
    data = mx.nd.array(np.random.randn(8, 3, 4, 4).astype(np.float32) * 2 + 1)
    gamma = mx.nd.ones((3,))
    beta = mx.nd.zeros((3,))
    mmean = mx.nd.zeros((3,))
    mvar = mx.nd.ones((3,))
    with ag.record(train_mode=True):
        out = mx.nd.BatchNorm(data, gamma, beta, mmean, mvar, fix_gamma=True,
                              momentum=0.9)
    # out normalized per channel
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-3
    # aux updated in place: moving_mean moved toward batch mean
    assert abs(mmean.asnumpy().mean()) > 1e-3


def test_second_use_of_input():
    # diamond: y = a*b where a = x+1, b = x*2 → dy/dx = b + 2a
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        a = x + 1
        b = x * 2
        y = a * b
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0 + 6.0])


def test_conv_grad_finite_diff():
    np.random.seed(0)
    data = np.random.randn(2, 3, 5, 5).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32) * 0.1
    b = np.zeros(4, dtype=np.float32)
    d_nd, w_nd, b_nd = mx.nd.array(data), mx.nd.array(w), mx.nd.array(b)
    for v in (d_nd, w_nd, b_nd):
        v.attach_grad()
    with ag.record():
        out = mx.nd.Convolution(d_nd, w_nd, b_nd, kernel=(3, 3),
                                num_filter=4, pad=(1, 1))
        loss = mx.nd.sum(out * out)
    loss.backward()
    # finite difference on one weight element
    eps = 1e-2
    w2 = w.copy()
    w2[0, 0, 0, 0] += eps
    out2 = mx.nd.Convolution(mx.nd.array(data), mx.nd.array(w2),
                             mx.nd.array(b), kernel=(3, 3), num_filter=4,
                             pad=(1, 1))
    l2 = float(mx.nd.sum(out2 * out2).asscalar())
    w3 = w.copy()
    w3[0, 0, 0, 0] -= eps
    out3 = mx.nd.Convolution(mx.nd.array(data), mx.nd.array(w3),
                             mx.nd.array(b), kernel=(3, 3), num_filter=4,
                             pad=(1, 1))
    l3 = float(mx.nd.sum(out3 * out3).asscalar())
    fd = (l2 - l3) / (2 * eps)
    np.testing.assert_allclose(w_nd.grad.asnumpy()[0, 0, 0, 0], fd,
                               rtol=2e-2)


def test_function_custom_backward():
    """autograd.Function: the user backward replaces the op vjp
    (reference python/mxnet/autograd.py:291 sigmoid example)."""

    class sigmoid(ag.Function):
        def forward(self, x):
            y = 1 / (1 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array(np.random.RandomState(0)
                    .randn(3, 4).astype(np.float32))
    x.attach_grad()
    func = sigmoid()
    with ag.record():
        y = func(x)
        loss = mx.nd.sum(y * y)
    loss.backward()
    sx = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), sx, rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               2 * sx * sx * (1 - sx), rtol=1e-5)


def test_function_composes_with_taped_ops_and_grad():
    """A Function node in the middle of a taped chain: gradients flow
    through the custom backward, and ag.grad sees it too."""

    class scale_by_three(ag.Function):
        def forward(self, x):
            return x * 3

        def backward(self, dy):
            return dy * 3

    x = mx.nd.array([0.5, -1.0, 2.0])
    x.attach_grad()
    with ag.record():
        h = x * x           # taped op
        f = scale_by_three()
        y = f(h)            # custom node
        loss = mx.nd.sum(y)
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                               rtol=1e-6)
    x2 = mx.nd.array([0.5, -1.0, 2.0])
    with ag.record():
        loss2 = mx.nd.sum(scale_by_three()(x2 * x2))
    g, = ag.grad(loss2, [x2])
    np.testing.assert_allclose(g.asnumpy(), 6 * x2.asnumpy(), rtol=1e-6)


def test_function_straight_through_and_reuse_rejected():
    """The canonical use case the true derivative can't express: a
    straight-through sign estimator.  Also: one record per instance."""
    import pytest

    from incubator_mxnet_tpu.base import MXNetError

    class sign_st(ag.Function):
        def forward(self, x):
            return mx.nd.sign(x)

        def backward(self, dy):
            return dy  # straight-through: pretend d sign/dx = 1

    x = mx.nd.array([-0.3, 0.0, 1.7])
    x.attach_grad()
    f = sign_st()
    with ag.record():
        y = f(x)
        loss = mx.nd.sum(y * mx.nd.array([1.0, 2.0, 3.0]))
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0, 2.0, 3.0])
    with ag.record():
        with pytest.raises(MXNetError, match="single call"):
            f(x)
