"""Run one PS-cluster role as a standalone process (fault-test helper).

Usage:
    python ps_node.py scheduler <num_workers> <num_servers> <port>
    python ps_node.py server <server_id> <num_workers> <sched_host> <port>

A server started with DMLC_PS_RECOVERY=1 is a replacement for a dead
server: it bootstraps its config from the scheduler and lets the first
worker re-seed its store (ps::Postoffice::is_recovery analog).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from incubator_mxnet_tpu import ps  # noqa: E402


def main():
    role = sys.argv[1]
    if role == "scheduler":
        nw, ns, port = int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
        sched = ps.Scheduler(nw, ns, port=port)
        print("scheduler up %s:%d" % (sched.host, sched.port), flush=True)
        sched.run()
    elif role == "server":
        sid, nw = int(sys.argv[2]), int(sys.argv[3])
        host, port = sys.argv[4], int(sys.argv[5])
        ps.bind_runtime()
        srv = ps.PSServer(sid, nw, (host, port))
        srv.start()
        srv.register()
        print("server %d up %s:%d recovery=%s"
              % (sid, srv.host, srv.port, srv.recovery), flush=True)
        srv._stopped.wait()
    else:
        raise SystemExit("unknown role %r" % role)


if __name__ == "__main__":
    main()
