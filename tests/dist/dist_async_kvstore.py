"""dist_async worker script — run under ``tools/launch.py -n 2 -s 2``.

Async contract (``kvstore_dist_server.h:154`` async branch): the server
applies every worker's push immediately — no cross-worker merge — so after
all workers push ``NREPEAT`` ones through the ``test`` updater
(w += rate·g) and then barrier, the pulled value is exactly
``init + rate·NREPEAT·nworker`` even though the per-push interleaving is
racy.  Includes a big range-sharded key (kvstore_dist.h:302-330).
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402

SHAPES = {"w": (8, 8), "big": (2048, 64)}  # big: 131072 rows*cols > bound
RATE = 2
NREPEAT = 4


def main():
    os.environ.setdefault("KVSTORE_BIGARRAY_BOUND", str(1 << 16))
    kv = mx.kv.create("dist_async")
    nworker = kv.num_workers
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=RATE))
    for k, s in SHAPES.items():
        kv.init(k, mx.nd.ones(s))
    for _ in range(NREPEAT):
        for k, s in SHAPES.items():
            kv.push(k, mx.nd.ones(s))
    kv.barrier()
    for k, s in SHAPES.items():
        out = mx.nd.zeros(s)
        kv.pull(k, out=out)
        expected = 1 + RATE * NREPEAT * nworker
        got = out.asnumpy()
        assert (got == expected).all(), \
            "key %s: got %s expected %s" % (k, np.unique(got), expected)
    dead = kv.get_dead_nodes(timeout=600)
    assert dead == [], dead
    kv._barrier_before_exit()
    print("dist_async_kvstore rank %d/%d: OK" % (kv.rank, nworker),
          flush=True)


if __name__ == "__main__":
    main()
