"""Exact-value dist_sync worker script — run under ``tools/launch.py -n 4``.

Port of ``/root/reference/tests/nightly/dist_sync_kvstore.py:36-55``: with
the ``test`` optimizer (w += rescale·grad), after each worker pushes ones
``nrepeat`` times, every key must be exactly
``init + rate·nrepeat·nworker`` — integer-exact, so any dropped or
double-counted message fails the assert.  Includes a key larger than the
big-array bound.
"""
import os
import sys

# worker processes must pin the CPU platform before jax initializes
# (conftest does this for in-process tests; launched processes need it here)
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402

SHAPES = {"3": (4, 4), "99": (700, 100)}  # 70000 > default big bound/8
RATE = 2
NREPEAT = 3


def main():
    kv = mx.kv.create("dist_sync")
    nworker = kv.num_workers
    assert nworker == int(os.environ["DMLC_NUM_WORKER"]), \
        (nworker, os.environ["DMLC_NUM_WORKER"])
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=RATE))
    for k, s in SHAPES.items():
        kv.init(k, mx.nd.ones(s))
    kv.barrier()
    for _ in range(NREPEAT):
        for k, s in SHAPES.items():
            kv.push(k, mx.nd.ones(s))
    kv.barrier()
    for k, s in SHAPES.items():
        out = mx.nd.zeros(s)
        kv.pull(k, out=out)
        expected = 1 + RATE * NREPEAT * nworker
        got = out.asnumpy()
        assert (got == expected).all(), \
            "key %s: got %s expected %s" % (k, np.unique(got), expected)
    print("dist_sync_kvstore rank %d/%d: OK" % (kv.rank, nworker),
          flush=True)


if __name__ == "__main__":
    main()
