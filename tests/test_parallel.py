"""Mesh/collectives/fused-step tests on the virtual 8-device CPU mesh —
the TPU-native analog of the reference nightly multi-device tests
(``tests/nightly/multi_lenet.py``, ``test_kvstore.py``)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel


def _mlp(nclass=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=nclass)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_build_mesh():
    import jax

    mesh = parallel.build_mesh({"dp": 4, "tp": 2})
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("dp", "tp")


def test_collectives_shard_map():
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel.mesh import shard_map_fn

    shard_map = shard_map_fn()

    mesh = parallel.build_mesh({"dp": 8})
    P = jax.sharding.PartitionSpec

    def f(x):
        return parallel.all_reduce(x, "dp")

    x = jnp.arange(8.0).reshape(8, 1)
    out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.full((8, 1), np.arange(8.0).sum()))


def test_ring_permute():
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel.mesh import shard_map_fn

    shard_map = shard_map_fn()

    mesh = parallel.build_mesh({"dp": 8})
    P = jax.sharding.PartitionSpec

    def f(x):
        return parallel.ring_permute(x, "dp", shift=1)

    x = jnp.arange(8.0).reshape(8, 1)
    out = np.asarray(shard_map(f, mesh=mesh, in_specs=P("dp"),
                               out_specs=P("dp"))(x)).reshape(-1)
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_fused_step_trains():
    rng = np.random.RandomState(0)
    nclass, dim = 4, 16
    centers = rng.randn(nclass, dim).astype(np.float32) * 3
    y = rng.randint(0, nclass, 256)
    x = centers[y] + rng.randn(256, dim).astype(np.float32)

    mesh = parallel.build_mesh({"dp": 8})
    step = parallel.FusedTrainStep(
        _mlp(nclass), {"data": (64, dim)}, {"softmax_label": (64,)},
        mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
        initializer=mx.initializer.Xavier())

    accs = []
    for epoch in range(6):
        correct = 0
        for i in range(0, 256, 64):
            outs = step({"data": x[i:i + 64],
                         "softmax_label": y[i:i + 64].astype(np.float32)})
            pred = np.asarray(outs[0]).argmax(1)
            correct += (pred == y[i:i + 64]).sum()
        accs.append(correct / 256)
    assert accs[-1] > 0.9, "fused dp step failed to learn: %s" % accs


def test_fused_step_matches_module():
    # numerical equivalence: fused sharded step ≡ Module single-device
    rng = np.random.RandomState(1)
    x = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 2, 64).astype(np.float32)

    net = _mlp(2)
    mesh = parallel.build_mesh({"dp": 4})
    step = parallel.FusedTrainStep(
        net, {"data": (64, 8)}, {"softmax_label": (64,)}, mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    arg0, _ = step.get_params()
    arg0 = {k: v.asnumpy().copy() for k, v in arg0.items()}

    for _ in range(3):
        step({"data": x, "softmax_label": y})
    fused_params = {k: v.asnumpy() for k, v in step.get_params()[0].items()}

    it = mx.io.NDArrayIter(x, y, batch_size=64)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.set_params({k: mx.nd.array(v) for k, v in arg0.items()}, {})
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for _ in range(3):
        it.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
    mod_params, _ = mod.get_params()
    for k in fused_params:
        np.testing.assert_allclose(fused_params[k],
                                   mod_params[k].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_fused_step_dynamic_lr_no_recompile():
    net = _mlp(2)
    step = parallel.FusedTrainStep(
        net, {"data": (16, 8)}, {"softmax_label": (16,)},
        mesh=parallel.build_mesh({"dp": 2}), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1,
                          "lr_scheduler":
                          mx.lr_scheduler.FactorScheduler(step=2,
                                                          factor=0.5)})
    x = np.random.rand(16, 8).astype(np.float32)
    y = np.zeros(16, np.float32)
    for _ in range(5):
        step({"data": x, "softmax_label": y})
    assert step.num_update == 5


def test_fused_step_flat_optimizer_matches_per_param():
    """flat_optimizer=True (one concatenated update kernel) is
    numerically identical to the per-parameter update path."""
    net = _mlp(4)
    rng = np.random.RandomState(7)
    x = rng.rand(16, 8).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.float32)

    def run(flat):
        mx.random.seed(5)  # initializer draws from the global stream
        step = parallel.FusedTrainStep(
            net, {"data": (16, 8)}, {"softmax_label": (16,)},
            mesh=parallel.build_mesh({"dp": 2}), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-3},
            initializer=mx.initializer.Uniform(0.07), seed=3,
            flat_optimizer=flat)
        for _ in range(4):
            step({"data": x, "softmax_label": y})
        return {k: np.asarray(v) for k, v in step.params.items()}

    ref = run(False)
    flat = run(True)
    for k in ref:
        np.testing.assert_allclose(flat[k], ref[k], rtol=1e-6,
                                   atol=1e-7, err_msg=k)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """save_sharded/restore_sharded resume a FusedTrainStep bit-exact,
    preserving tp-partitioned shardings (the at-scale checkpoint path;
    the two-file host format stays for API parity)."""
    import jax

    from incubator_mxnet_tpu.parallel.checkpoint import (restore_sharded,
                                                         save_sharded)

    P = jax.sharding.PartitionSpec
    net = _mlp(4)
    mx.random.seed(11)
    mesh = parallel.build_mesh({"dp": 4, "tp": 2})
    kw = dict(mesh=mesh, optimizer="sgd",
              optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
              param_partition={"fc2_weight": P("tp", None),
                               "fc2_bias": P("tp")})
    step = parallel.FusedTrainStep(
        net, {"data": (16, 8)}, {"softmax_label": (16,)}, **kw)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 8).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.float32)
    for _ in range(3):
        step({"data": x, "softmax_label": y})
    want = {k: np.asarray(v) for k, v in step.params.items()}
    ckpt = str(tmp_path / "ckpt")
    save_sharded(ckpt, step)

    mx.random.seed(12)  # fresh different init
    step2 = parallel.FusedTrainStep(
        net, {"data": (16, 8)}, {"softmax_label": (16,)}, **kw)
    restore_sharded(ckpt, step2)
    assert step2.num_update == 3
    for k in want:
        np.testing.assert_array_equal(np.asarray(step2.params[k]),
                                      want[k], err_msg=k)
    # shardings preserved: the tp-partitioned weight is still partitioned
    assert not step2.params["fc2_weight"].sharding.is_fully_replicated
    # and training continues from the restored state identically
    step({"data": x, "softmax_label": y})
    step2({"data": x, "softmax_label": y})
    for k in want:
        np.testing.assert_allclose(np.asarray(step2.params[k]),
                                   np.asarray(step.params[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)


def test_grad_accum_matches_full_batch():
    """grad_accum=k sums microbatch gradients into ONE update — exactly
    the full-batch step for BN-free nets (BN nets get microbatch
    statistics, the standard grad-accum semantics)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel

    d = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.FullyConnected(x, num_hidden=5, name="fc2")
    net = mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                               name="softmax")
    rng = np.random.RandomState(0)
    batch = {"data": rng.randn(8, 12).astype(np.float32),
             "softmax_label": rng.randint(0, 5, (8,)).astype(np.float32)}
    results = {}
    for accum in (1, 4):
        mx.random.seed(0)
        step = parallel.FusedTrainStep(
            net, {"data": (8, 12)}, {"softmax_label": (8,)},
            mesh=parallel.default_mesh(1), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), seed=0,
            grad_accum=accum)
        outs = None
        for _ in range(3):
            outs = step(batch)
        results[accum] = (
            {n: np.asarray(v) for n, v in step.params.items()},
            np.asarray(outs[0]))
    p1, o1 = results[1]
    p4, o4 = results[4]
    assert o4.shape == o1.shape  # outputs restack to the full batch
    for n in p1:
        np.testing.assert_allclose(p1[n], p4[n], rtol=1e-5, atol=1e-7,
                                   err_msg=n)
    np.testing.assert_allclose(o1, o4, rtol=1e-4, atol=1e-6)


def test_grad_accum_guards():
    """Explicit grad_accum wins over env; non-batch-major inputs and
    indivisible batches are refused with clear errors."""
    import os

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.base import MXNetError

    d = mx.sym.Variable("data")
    net = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(d, num_hidden=4, name="fc"),
        mx.sym.Variable("label"), name="lro")

    os.environ["TP_GRAD_ACCUM"] = "4"
    try:
        step = parallel.FusedTrainStep(
            net, {"data": (8, 6)}, {"label": (8, 4)},
            mesh=parallel.default_mesh(1), grad_accum=1)
        assert step._accum == 1  # explicit 1 pins accumulation OFF
        step_env = parallel.FusedTrainStep(
            net, {"data": (8, 6)}, {"label": (8, 4)},
            mesh=parallel.default_mesh(1))
        assert step_env._accum == 4  # unspecified -> env applies
    finally:
        del os.environ["TP_GRAD_ACCUM"]

    with pytest.raises(MXNetError, match="does not divide"):
        parallel.FusedTrainStep(net, {"data": (8, 6)},
                                {"label": (8, 4)},
                                mesh=parallel.default_mesh(1),
                                grad_accum=3)
    # time-major label (leading dim != batch) must be refused
    net2 = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(d, num_hidden=8, name="fc"),
        mx.sym.Variable("label"), name="lro")
    with pytest.raises(MXNetError, match="batch-major"):
        parallel.FusedTrainStep(net2, {"data": (8, 6)},
                                {"label": (4, 16)},
                                mesh=parallel.default_mesh(1),
                                grad_accum=2)


def test_opt_state_dtype_bf16_converges():
    """opt_state_dtype='bfloat16' halves the m/v streams; update math
    stays f32 (upcast/downcast), so training tracks the f32-state run
    closely and states are stored bf16."""
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel

    d = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.FullyConnected(x, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                               name="softmax")
    rng = np.random.RandomState(0)
    data = rng.randn(16, 8).astype(np.float32)
    labels = rng.randint(0, 4, (16,)).astype(np.float32)
    runs = {}
    for sdt in (None, "bfloat16"):
        mx.random.seed(1)
        step = parallel.FusedTrainStep(
            net, {"data": (16, 8)}, {"softmax_label": (16,)},
            mesh=parallel.default_mesh(1), optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), seed=0,
            opt_state_dtype=sdt)
        if sdt:
            assert all(s.dtype == jnp.bfloat16
                       for st in step.opt_states.values() for s in st)
        for _ in range(20):
            outs = step({"data": data, "softmax_label": labels})
        probs = np.asarray(outs[0])
        nll = -np.log(probs[np.arange(16), labels.astype(int)] + 1e-9)
        runs[sdt] = nll.mean()
    # both train to a similar loss (bf16 states are a rounding, not a
    # different algorithm)
    assert runs["bfloat16"] < 1.2 * runs[None] + 0.05, runs


def test_grad_accum_rejects_non_null_head_normalization():
    """A fused softmax-xent head with normalization='batch'/'valid'
    divides by the MICROBATCH count, so accumulated grads would come
    out k-fold too large — FusedTrainStep refuses the combination."""
    from incubator_mxnet_tpu.base import MXNetError

    def lm(norm):
        x = mx.sym.Variable("data")
        lab = mx.sym.Variable("label")
        w = mx.sym.Variable("head_weight")
        return mx.sym.SoftmaxXentHead(x, w, lab, num_hidden=5,
                                      normalization=norm,
                                      name="softmax")

    with pytest.raises(MXNetError, match="normalization"):
        parallel.FusedTrainStep(lm("batch"), {"data": (8, 4)},
                                {"label": (8,)},
                                mesh=parallel.default_mesh(1),
                                grad_accum=2)
    # the accumulation-invariant default is accepted
    step = parallel.FusedTrainStep(lm("null"), {"data": (8, 4)},
                                   {"label": (8,)},
                                   mesh=parallel.default_mesh(1),
                                   grad_accum=2)
    assert step._accum == 2


def test_grad_dtype_bf16_converges():
    """grad_dtype='bfloat16' casts gradients at the backward boundary
    (accumulators + dp all-reduce at half width); update math upcasts
    to f32 masters, so training tracks the f32-grad run — including
    under grad_accum, where the accumulator itself is bf16."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel

    d = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    x = mx.sym.Activation(x, act_type="relu", name="r1")
    x = mx.sym.FullyConnected(x, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                               name="softmax")
    rng = np.random.RandomState(0)
    data = rng.randn(16, 8).astype(np.float32)
    labels = rng.randint(0, 4, (16,)).astype(np.float32)
    runs = {}
    for gdt, accum in ((None, 1), ("bfloat16", 1), ("bfloat16", 4)):
        mx.random.seed(1)
        step = parallel.FusedTrainStep(
            net, {"data": (16, 8)}, {"softmax_label": (16,)},
            mesh=parallel.default_mesh(1), optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), seed=0,
            grad_dtype=gdt, grad_accum=accum)
        for _ in range(20):
            outs = step({"data": data, "softmax_label": labels})
        probs = np.asarray(outs[0])
        nll = -np.log(probs[np.arange(16), labels.astype(int)] + 1e-9)
        runs[(gdt, accum)] = nll.mean()
    base = runs[(None, 1)]
    assert runs[("bfloat16", 1)] < 1.2 * base + 0.05, runs
    assert runs[("bfloat16", 4)] < 1.3 * base + 0.1, runs
