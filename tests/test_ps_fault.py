"""Parameter-server fault handling: clean failure + recovery.

Reference analog: ps-lite's liveness machinery —
``ps::Postoffice::GetDeadNodes`` (kvstore_dist.h:177-190) and the
``is_recovery()`` rejoin semantics that skip barriers
(kvstore_dist.h:57,95,196).  The reference has no server-state recovery;
here the worker re-seeds a replacement server from its freshest pulled
weights, so this suite asserts MORE than parity: a killed server either
surfaces a clean error (default) or is transparently replaced
(TP_PS_RECOVERY).
"""
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from incubator_mxnet_tpu import ps
from incubator_mxnet_tpu.base import MXNetError

HERE = os.path.dirname(os.path.abspath(__file__))
NODE = os.path.join(HERE, "dist", "ps_node.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, env=None):
    full_env = dict(os.environ)
    full_env["JAX_PLATFORMS"] = "cpu"
    if env:
        full_env.update(env)
    return subprocess.Popen([sys.executable, NODE] + [str(a) for a in args],
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True, env=full_env)


class _Cluster:
    """scheduler + N server subprocesses; the client runs in-process."""

    def __init__(self, num_servers=2, num_workers=1):
        self.port = _free_port()
        self.num_workers = num_workers
        self.sched = _spawn(["scheduler", num_workers, num_servers,
                             self.port])
        self.servers = [
            _spawn(["server", i, num_workers, "127.0.0.1", self.port])
            for i in range(num_servers)]
        self.procs = [self.sched] + self.servers

    def kill_server(self, idx):
        self.servers[idx].send_signal(signal.SIGKILL)
        self.servers[idx].wait(timeout=30)

    def respawn_server(self, idx):
        self.servers[idx] = _spawn(
            ["server", idx, self.num_workers, "127.0.0.1", self.port],
            env={"DMLC_PS_RECOVERY": "1"})
        self.procs.append(self.servers[idx])

    def shutdown(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


@pytest.fixture
def cluster():
    c = _Cluster(num_servers=2, num_workers=1)
    yield c
    c.shutdown()


def _owner_of(client, key, arr):
    (sidx, _, _), = client._plan(key, arr)
    return sidx


@pytest.mark.slow
def test_server_death_is_a_clean_error(cluster):
    """Default mode: a dead server surfaces as MXNetError naming the
    server and the scheduler's dead-node view — not a raw socket trace."""
    c = ps.PSClient(0, scheduler=("127.0.0.1", cluster.port),
                    recover_servers=False)
    w = np.arange(8, dtype=np.float32)
    c.init("w", w)
    np.testing.assert_array_equal(c.pull("w", w), w)

    cluster.kill_server(_owner_of(c, "w", w))
    with pytest.raises(MXNetError, match="unreachable"):
        for _ in range(3):  # first op after death must already fail clean
            c.push("w", w)
            time.sleep(0.2)


@pytest.mark.slow
def test_server_death_recovery_reseed(cluster):
    """TP_PS_RECOVERY path: kill the owning server mid-run, start a
    replacement (DMLC_PS_RECOVERY=1), and the same worker continues —
    weights resume from its freshest pulled copy."""
    c = ps.PSClient(0, scheduler=("127.0.0.1", cluster.port),
                    recover_servers=True)
    w0 = np.full(8, 1.0, np.float32)
    c.init("w", w0)
    # async semantics without an updater: push stores the value
    c.push("w", np.full(8, 2.0, np.float32))
    np.testing.assert_array_equal(c.pull("w", w0), 2.0)  # caches 2.0

    victim = _owner_of(c, "w", w0)
    cluster.kill_server(victim)
    cluster.respawn_server(victim)

    # next op transparently waits for the replacement, re-seeds it with
    # the cached 2.0 weights, then applies the push
    c.push("w", np.full(8, 3.0, np.float32))
    np.testing.assert_array_equal(c.pull("w", w0), 3.0)

    # an untouched key on the re-seeded server still resolves after a
    # fresh pull-after-reseed round-trip
    c.init("v", np.full(8, 7.0, np.float32))
    np.testing.assert_array_equal(c.pull("v", w0), 7.0)
    c.finalize()


@pytest.mark.slow
def test_recovering_node_skips_barriers(cluster):
    """A node marked DMLC_PS_RECOVERY=1 must not count toward (or block
    on) barriers — the is_recovery contract, kvstore_dist.h:57,95,196."""
    os.environ["DMLC_PS_RECOVERY"] = "1"
    try:
        c = ps.PSClient(0, scheduler=("127.0.0.1", cluster.port))
    finally:
        del os.environ["DMLC_PS_RECOVERY"]
    assert c.is_recovery
    t0 = time.time()
    # num_workers=1 but barrier ids are fresh: a non-recovery client
    # would release instantly too, so assert via a 2-worker scheduler
    # expectation instead: the recovery client returns immediately even
    # for a barrier no other node ever joins
    c2 = _Cluster(num_servers=1, num_workers=2)
    try:
        cr = ps.PSClient(1, scheduler=("127.0.0.1", c2.port))
        cr.is_recovery = True
        cr.barrier("never-joined-by-anyone")
        assert time.time() - t0 < 30
    finally:
        c2.shutdown()


@pytest.mark.slow
def test_replacement_server_bootstraps_config(cluster):
    """set_sync/set_optimizer are parked at the scheduler; a replacement
    server picks them up at register time (no un-configured window)."""
    c = ps.PSClient(0, scheduler=("127.0.0.1", cluster.port),
                    recover_servers=True)
    c.set_sync(False)
    from incubator_mxnet_tpu import optimizer as opt

    c.set_optimizer(opt.create("sgd", learning_rate=0.5,
                               rescale_grad=1.0))
    w = np.zeros(4, np.float32)
    c.init("w", w)

    victim = _owner_of(c, "w", w)
    cluster.kill_server(victim)
    cluster.respawn_server(victim)

    # with the sgd updater live on the REPLACEMENT server:
    # w <- w - lr * grad = 0 - 0.5 * 1 = -0.5
    c.push("w", np.ones(4, np.float32))
    np.testing.assert_allclose(c.pull("w", w), -0.5, rtol=1e-6)


@pytest.mark.slow
def test_late_stale_reseed_does_not_roll_back(cluster):
    """Two workers recover at different times: the late worker's stale
    re-seed must not roll back updates applied after the first re-seed,
    and a legitimate re-init after recovery must apply normally."""
    c2 = _Cluster(num_servers=1, num_workers=2)
    try:
        c0 = ps.PSClient(0, scheduler=("127.0.0.1", c2.port),
                         recover_servers=True)
        c1 = ps.PSClient(1, scheduler=("127.0.0.1", c2.port),
                         recover_servers=True)
        w = np.zeros(4, np.float32)
        c0.init("w", np.full(4, 1.0, np.float32))
        c1.init("w", np.full(4, 1.0, np.float32))
        c0.push("w", np.full(4, 2.0, np.float32))
        c1.pull("w", w)  # c1's local re-seed copy caches 2.0
        c0.push("w", np.full(4, 5.0, np.float32))
        c0.pull("w", w)  # c0 caches 5.0; c1 stays stale at 2.0

        c2.kill_server(0)
        c2.respawn_server(0)

        # c0 trips first: re-seeds 5.0, applies its push
        c0.push("w", np.full(4, 6.0, np.float32))
        np.testing.assert_array_equal(c0.pull("w", w), 6.0)
        # c1 trips later: its stale 2.0 re-seed must be ignored
        c1.push("w", np.full(4, 7.0, np.float32))
        np.testing.assert_array_equal(c1.pull("w", w), 7.0)
        np.testing.assert_array_equal(c0.pull("w", w), 7.0)

        # a legitimate (untagged) re-init still applies on the
        # replacement, identically to a healthy server
        c0.init("w", np.full(4, 9.0, np.float32))
        np.testing.assert_array_equal(c0.pull("w", w), 9.0)
        c0.finalize()
    finally:
        c2.shutdown()
