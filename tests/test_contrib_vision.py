"""Numeric tests for the contrib vision/sequence tier: Correlation,
CTCLoss, PSROIPooling, DeformablePSROIPooling, DeformableConvolution,
krprod — numpy loop oracles transcribed from the reference kernels, plus
brute-force path enumeration for CTC."""
import itertools

import numpy as np

from incubator_mxnet_tpu.ops.registry import get_op

from test_operator import apply_op, check_fwd, check_grad_fd


# ---------------------------------------------------------------------------
# Correlation — oracle from correlation.cc:40-80
# ---------------------------------------------------------------------------

def _np_correlation(d1, d2, pad, ksize, max_disp, s1, s2, is_mult):
    n, c, h, w = d1.shape
    kr = (ksize - 1) // 2
    border = max_disp + kr
    ph, pw = h + 2 * pad, w + 2 * pad
    top_w = int(np.ceil((pw - 2 * border) / s1))
    top_h = int(np.ceil((ph - 2 * border) / s1))
    rad = max_disp // s2
    gw = 2 * rad + 1
    p1 = np.pad(d1.astype(np.float64), [(0, 0), (0, 0), (pad, pad),
                                        (pad, pad)])
    p2 = np.pad(d2.astype(np.float64), [(0, 0), (0, 0), (pad, pad),
                                        (pad, pad)])
    out = np.zeros((n, gw * gw, top_h, top_w))
    sumelems = ksize * ksize * c
    for i in range(top_h):
        for j in range(top_w):
            x1 = j * s1 + max_disp
            y1 = i * s1 + max_disp
            for tc in range(gw * gw):
                s2o = (tc % gw - rad) * s2
                s2p = (tc // gw - rad) * s2
                x2, y2 = x1 + s2o, y1 + s2p
                a = p1[:, :, y1:y1 + ksize, x1:x1 + ksize]
                b = p2[:, :, y2:y2 + ksize, x2:x2 + ksize]
                v = a * b if is_mult else np.abs(a - b)
                out[:, tc, i, j] = v.sum(axis=(1, 2, 3)) / sumelems
    return out


def test_correlation():
    rng = np.random.RandomState(0)
    d1 = rng.randn(2, 3, 6, 6).astype(np.float32)
    d2 = rng.randn(2, 3, 6, 6).astype(np.float32)
    attrs = {"kernel_size": "1", "max_displacement": "2", "stride1": "1",
             "stride2": "1", "pad_size": "2"}
    want = _np_correlation(d1, d2, 2, 1, 2, 1, 1, True)
    check_fwd("Correlation", [d1, d2], want, attrs, rtol=1e-4, atol=1e-4)
    # kernel window > 1, strides > 1, abs-difference mode
    attrs2 = {"kernel_size": "3", "max_displacement": "2", "stride1": "2",
              "stride2": "2", "pad_size": "3", "is_multiply": "0"}
    want2 = _np_correlation(d1, d2, 3, 3, 2, 2, 2, False)
    check_fwd("Correlation", [d1, d2], want2, attrs2, rtol=1e-4, atol=1e-4)
    # shape inference
    op = get_op("Correlation")
    _, outs, _ = op.infer_shape([(2, 3, 6, 6), (2, 3, 6, 6)], attrs)
    assert outs[0] == want.shape
    check_grad_fd("Correlation", [d1[:1, :1, :4, :4], d2[:1, :1, :4, :4]],
                  {"kernel_size": "1", "max_displacement": "1",
                   "pad_size": "1"}, wrt=(0, 1))


# ---------------------------------------------------------------------------
# CTCLoss — brute-force path enumeration oracle
# ---------------------------------------------------------------------------

def _collapse(path):
    out = []
    prev = None
    for s in path:
        if s != prev and s != 0:
            out.append(s)
        prev = s
    return tuple(out)


def _np_ctc_loss(data, labels):
    """-log P(label) by enumerating every alignment path (tiny T/C only)."""
    T, N, C = data.shape
    e = np.exp(data.astype(np.float64)
               - data.astype(np.float64).max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    losses = []
    for b in range(N):
        target = tuple(int(v) for v in labels[b] if v != 0)
        p_total = 0.0
        for path in itertools.product(range(C), repeat=T):
            if _collapse(path) == target:
                p = 1.0
                for t, s in enumerate(path):
                    p *= probs[t, b, s]
                p_total += p
        losses.append(-np.log(p_total))
    return np.array(losses)


def test_ctc_loss():
    rng = np.random.RandomState(1)
    T, N, C, L = 4, 3, 3, 2
    data = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2], [1, 0], [2, 2]], np.float32)  # 0 = pad/blank
    want = _np_ctc_loss(data, labels)
    for name in ("_contrib_CTCLoss", "CTCLoss", "ctc_loss"):
        check_fwd(name, [data, labels], want, rtol=1e-4, atol=1e-4)
    # gradient flows through the activations (finite-diff check)
    check_grad_fd("ctc_loss", [data[:, :1], labels[:1]], wrt=(0,))
    op = get_op("_contrib_CTCLoss")
    _, outs, _ = op.infer_shape([(T, N, C), (N, L)], {})
    assert outs[0] == (N,)


def test_ctc_loss_longer_alphabet():
    rng = np.random.RandomState(2)
    T, N, C = 5, 2, 4
    data = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[3, 1, 0], [2, 0, 0]], np.float32)
    want = _np_ctc_loss(data, labels)
    check_fwd("ctc_loss", [data, labels], want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# PSROIPooling — oracle from psroi_pooling.cu:50-116
# ---------------------------------------------------------------------------

def _np_psroi_pool(data, rois, scale, out_dim, pooled, gsize):
    n, channels, height, width = data.shape
    r = rois.shape[0]
    out = np.zeros((r, out_dim, pooled, pooled))
    for ri in range(r):
        batch = int(rois[ri, 0])
        x1 = round(float(rois[ri, 1])) * scale
        y1 = round(float(rois[ri, 2])) * scale
        x2 = (round(float(rois[ri, 3])) + 1.0) * scale
        y2 = (round(float(rois[ri, 4])) + 1.0) * scale
        rw = max(x2 - x1, 0.1)
        rh = max(y2 - y1, 0.1)
        bh, bw = rh / pooled, rw / pooled
        for ct in range(out_dim):
            for ph in range(pooled):
                for pw in range(pooled):
                    hs = min(max(int(np.floor(ph * bh + y1)), 0), height)
                    he = min(max(int(np.ceil((ph + 1) * bh + y1)), 0),
                             height)
                    ws = min(max(int(np.floor(pw * bw + x1)), 0), width)
                    we = min(max(int(np.ceil((pw + 1) * bw + x1)), 0),
                             width)
                    gh = min(max(ph * gsize // pooled, 0), gsize - 1)
                    gw = min(max(pw * gsize // pooled, 0), gsize - 1)
                    c = (ct * gsize + gh) * gsize + gw
                    if he <= hs or we <= ws:
                        continue
                    win = data[batch, c, hs:he, ws:we].astype(np.float64)
                    out[ri, ct, ph, pw] = win.sum() / ((he - hs) * (we - ws))
    return out


def test_psroi_pooling():
    rng = np.random.RandomState(3)
    out_dim, gsize, pooled = 2, 3, 3
    data = rng.randn(2, out_dim * gsize * gsize, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 31, 31],
                     [1, 8, 4, 24, 28],
                     [0, 14, 14, 15, 15]], np.float32)
    scale = 0.25
    want = _np_psroi_pool(data, rois, scale, out_dim, pooled, gsize)
    attrs = {"spatial_scale": str(scale), "output_dim": str(out_dim),
             "pooled_size": str(pooled), "group_size": str(gsize)}
    for name in ("_contrib_PSROIPooling", "PSROIPooling"):
        check_fwd(name, [data, rois], want, attrs, rtol=1e-4, atol=1e-4)
    op = get_op("_contrib_PSROIPooling")
    _, outs, _ = op.infer_shape([data.shape, rois.shape], attrs)
    assert outs[0] == (3, out_dim, pooled, pooled)
    check_grad_fd("PSROIPooling",
                  [data[:1, :, :4, :4] * 0.1, rois[:1]], attrs, wrt=(0,))


# ---------------------------------------------------------------------------
# DeformablePSROIPooling
# ---------------------------------------------------------------------------

def _np_dpsroi_pool(data, rois, trans, scale, out_dim, pooled, gsize,
                    part, spp, trans_std):
    n, channels, height, width = data.shape
    r = rois.shape[0]
    num_classes = 1 if trans is None else trans.shape[1] // 2
    cpc = max(out_dim // num_classes, 1)
    out = np.zeros((r, out_dim, pooled, pooled))

    def bil(img, h, w):
        h = min(max(h, 0.0), height - 1.0)
        w = min(max(w, 0.0), width - 1.0)
        h0, w0 = int(np.floor(h)), int(np.floor(w))
        h1, w1 = min(h0 + 1, height - 1), min(w0 + 1, width - 1)
        lh, lw = h - h0, w - w0
        return (img[h0, w0] * (1 - lh) * (1 - lw)
                + img[h0, w1] * (1 - lh) * lw
                + img[h1, w0] * lh * (1 - lw)
                + img[h1, w1] * lh * lw)

    for ri in range(r):
        batch = int(rois[ri, 0])
        x1 = round(float(rois[ri, 1])) * scale - 0.5
        y1 = round(float(rois[ri, 2])) * scale - 0.5
        x2 = (round(float(rois[ri, 3])) + 1.0) * scale - 0.5
        y2 = (round(float(rois[ri, 4])) + 1.0) * scale - 0.5
        rw = max(x2 - x1, 0.1)
        rh = max(y2 - y1, 0.1)
        bh, bw = rh / pooled, rw / pooled
        sbh, sbw = bh / spp, bw / spp
        for ct in range(out_dim):
            cls = ct // cpc
            for ph in range(pooled):
                for pw in range(pooled):
                    part_h = min(max(ph * part // pooled, 0), part - 1)
                    part_w = min(max(pw * part // pooled, 0), part - 1)
                    if trans is None:
                        tx = ty = 0.0
                    else:
                        tx = trans[ri, cls * 2, part_h, part_w] * trans_std
                        ty = trans[ri, cls * 2 + 1, part_h,
                                   part_w] * trans_std
                    ws = pw * bw + x1 + tx * rw
                    hs = ph * bh + y1 + ty * rh
                    gh = min(max(ph * gsize // pooled, 0), gsize - 1)
                    gw = min(max(pw * gsize // pooled, 0), gsize - 1)
                    c = (ct * gsize + gh) * gsize + gw
                    tot, cnt = 0.0, 0
                    for ih in range(spp):
                        for iw in range(spp):
                            w = ws + iw * sbw
                            h = hs + ih * sbh
                            if (w < -0.5 or w > width - 0.5 or h < -0.5
                                    or h > height - 0.5):
                                continue
                            tot += bil(
                                data[batch, c].astype(np.float64), h, w)
                            cnt += 1
                    out[ri, ct, ph, pw] = 0.0 if cnt == 0 else tot / cnt
    return out


def test_deformable_psroi_pooling():
    rng = np.random.RandomState(4)
    out_dim, gsize, pooled, spp = 2, 2, 2, 2
    data = rng.randn(1, out_dim * gsize * gsize, 8, 8).astype(np.float32)
    rois = np.array([[0, 2, 2, 28, 24], [0, 0, 0, 31, 31]], np.float32)
    scale = 0.25
    base_attrs = {"spatial_scale": str(scale), "output_dim": str(out_dim),
                  "pooled_size": str(pooled), "group_size": str(gsize),
                  "sample_per_part": str(spp)}
    # no_trans path
    attrs = dict(base_attrs, no_trans="1")
    want = _np_dpsroi_pool(data, rois, None, scale, out_dim, pooled,
                           gsize, pooled, spp, 0.0)
    for name in ("_contrib_DeformablePSROIPooling",
                 "DeformablePSROIPooling"):
        check_fwd(name, [data, rois], want, attrs, rtol=1e-4, atol=1e-4)
    # learned offsets
    trans = (rng.rand(2, 2, pooled, pooled).astype(np.float32) - 0.5)
    attrs_t = dict(base_attrs, trans_std="0.2")
    want_t = _np_dpsroi_pool(data, rois, trans, scale, out_dim, pooled,
                             gsize, pooled, spp, 0.2)
    check_fwd("DeformablePSROIPooling", [data, rois, trans], want_t,
              attrs_t, rtol=1e-4, atol=1e-4)
    # zero trans == no_trans
    zero = np.zeros_like(trans)
    check_fwd("DeformablePSROIPooling", [data, rois, zero], want,
              attrs_t, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

def test_deformable_convolution_zero_offset_matches_conv():
    rng = np.random.RandomState(5)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    attrs = {"kernel": "(3, 3)", "num_filter": "3", "stride": "(1, 1)",
             "pad": "(1, 1)"}
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    conv = apply_op("Convolution", [x, w, b], attrs)[0]
    for name in ("_contrib_DeformableConvolution", "DeformableConvolution"):
        out = apply_op(name, [x, off, w, b], attrs)[0]
        np.testing.assert_allclose(out, conv, rtol=1e-4, atol=1e-4)


def test_deformable_convolution_integer_shift():
    """A constant integer offset equals convolving a shifted input."""
    rng = np.random.RandomState(6)
    x = rng.randn(1, 1, 8, 8).astype(np.float32)
    w = rng.randn(2, 1, 3, 3).astype(np.float32)
    attrs = {"kernel": "(3, 3)", "num_filter": "2", "no_bias": "1"}
    # shift all sampling one pixel right (dx = 1): same as shifting the
    # input left by one column
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    off[:, 1::2] = 1.0
    out = apply_op("DeformableConvolution", [x, off, w], attrs)[0]
    x_shift = np.zeros_like(x)
    x_shift[:, :, :, :-1] = x[:, :, :, 1:]
    want = apply_op("Convolution", [x_shift, w], attrs)[0]
    # interior columns match exactly (border column differs: deformable
    # samples the true pixel beyond the crop, the shifted input zero-pads)
    np.testing.assert_allclose(out[..., :-1], want[..., :-1],
                               rtol=1e-4, atol=1e-4)


def test_deformable_convolution_fractional_offset_and_grad():
    rng = np.random.RandomState(7)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(2, 2, 3, 3).astype(np.float32)
    off = (rng.rand(1, 2 * 9, 3, 3).astype(np.float32) - 0.5)
    attrs = {"kernel": "(3, 3)", "num_filter": "2", "no_bias": "1"}
    out = apply_op("DeformableConvolution", [x, off, w], attrs)[0]
    assert out.shape == (1, 2, 3, 3)
    check_grad_fd("DeformableConvolution",
                  [x, off * 0.3, w], attrs, wrt=(0, 1, 2),
                  rtol=5e-2, atol=5e-2)
    op = get_op("_contrib_DeformableConvolution")
    shapes, outs, _ = op.infer_shape(
        [(1, 2, 5, 5), None, None],
        {"kernel": "(3, 3)", "num_filter": "2", "no_bias": "1"})
    assert outs[0] == (1, 2, 3, 3)
    assert shapes[1] == (1, 18, 3, 3) and shapes[2] == (2, 2, 3, 3)


def test_deformable_convolution_edge_semantics():
    """Exact deformable_im2col edge behavior: a sample at coordinate in
    (-1, 0) is zero (validity gate is >= 0), and a sample in the last
    fractional row snaps to the edge pixel with FULL weight (the
    h_low >= height-1 clamp resets lh to 0)."""
    x = np.zeros((1, 1, 2, 1), np.float32)
    x[0, 0, 0, 0] = 7.0
    x[0, 0, 1, 0] = 5.0
    w = np.ones((1, 1, 1, 1), np.float32)
    attrs = {"kernel": "(1, 1)", "num_filter": "1", "no_bias": "1"}
    # dy = -0.5 at the top pixel -> coordinate -0.5 -> exactly 0
    off = np.zeros((1, 2, 2, 1), np.float32)
    off[0, 0] = -0.5
    out = apply_op("DeformableConvolution", [x, off, w], attrs)[0]
    assert out[0, 0, 0, 0] == 0.0, out
    # dy = +0.5 at the bottom pixel -> 1.5 -> snaps to row 1, full weight
    off2 = np.zeros((1, 2, 2, 1), np.float32)
    off2[0, 0] = 0.5
    out2 = apply_op("DeformableConvolution", [x, off2, w], attrs)[0]
    np.testing.assert_allclose(out2[0, 0, 1, 0], 5.0, rtol=1e-6)
    # interior fractional sample still interpolates: row 0 at y=0.5
    np.testing.assert_allclose(out2[0, 0, 0, 0], 6.0, rtol=1e-6)


def test_deformable_convolution_groups():
    rng = np.random.RandomState(8)
    x = rng.randn(1, 4, 5, 5).astype(np.float32)
    w = rng.randn(4, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 2 * 9, 3, 3), np.float32)
    attrs = {"kernel": "(3, 3)", "num_filter": "4", "num_group": "2",
             "num_deformable_group": "2", "no_bias": "1"}
    out = apply_op("DeformableConvolution", [x, off, w], attrs)[0]
    want = apply_op("Convolution", [x, w],
                    {"kernel": "(3, 3)", "num_filter": "4",
                     "num_group": "2", "no_bias": "1"})[0]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# krprod
# ---------------------------------------------------------------------------

def test_krprod():
    rng = np.random.RandomState(9)
    a = rng.randn(3, 2).astype(np.float32)
    b = rng.randn(3, 4).astype(np.float32)
    c = rng.randn(3, 2).astype(np.float32)
    want2 = np.stack([np.kron(a[i], b[i]) for i in range(3)])
    for name in ("_contrib_krprod", "khatri_rao"):
        check_fwd(name, [a, b], want2, rtol=1e-5, atol=1e-5)
    want3 = np.stack([np.kron(np.kron(a[i], b[i]), c[i]) for i in range(3)])
    check_fwd("_contrib_krprod", [a, b, c], want3, rtol=1e-5, atol=1e-5)
    check_fwd("_contrib_krprod", [a], a)
