"""mx.rnn cell zoo tests — reference ``tests/python/unittest/test_rnn.py``
(shape checks per cell, fused-vs-unfused equivalence, pack/unpack
roundtrip) + BucketSentenceIter."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _exec_unrolled(outputs, states, data_shape, seed=0, extra=None):
    """Bind a Group of [outputs]+states, init uniformly, return arrays."""
    net = mx.sym.Group([outputs] + list(states)) if states else outputs
    shapes = {"data": data_shape}
    if extra:
        shapes.update(extra)
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null", **shapes)
    rng = np.random.RandomState(seed)
    for name, arr in sorted(ex.arg_dict.items()):
        arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
    ex.forward(is_train=False)
    return ex


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(50, prefix="rnn_")
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    assert sorted(cell.params._params.keys()) == \
        ["rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias",
         "rnn_i2h_weight"]
    ex = _exec_unrolled(outputs, states, (2, 3, 20))
    assert ex.outputs[0].shape == (2, 3, 50)
    assert ex.outputs[1].shape == (2, 50)


def test_lstm_cell_unroll_shapes():
    cell = mx.rnn.LSTMCell(50, prefix="lstm_")
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = _exec_unrolled(outputs, states, (2, 3, 20))
    assert ex.outputs[0].shape == (2, 3, 50)
    assert ex.outputs[1].shape == (2, 50)  # h
    assert ex.outputs[2].shape == (2, 50)  # c


def test_gru_cell_unroll_shapes():
    cell = mx.rnn.GRUCell(50, prefix="gru_")
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = _exec_unrolled(outputs, states, (2, 3, 20))
    assert ex.outputs[0].shape == (2, 3, 50)


def test_stacked_and_residual_and_dropout():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(32, prefix="l0_"))
    stack.add(mx.rnn.DropoutCell(0.3))
    stack.add(mx.rnn.ResidualCell(mx.rnn.LSTMCell(32, prefix="l1_")))
    outputs, states = stack.unroll(4, inputs=mx.sym.Variable("data"),
                                   merge_outputs=True)
    ex = _exec_unrolled(outputs, states, (2, 4, 32))
    assert ex.outputs[0].shape == (2, 4, 32)


def test_bidirectional_cell():
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(25, prefix="l_"),
        mx.rnn.LSTMCell(25, prefix="r_"))
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = _exec_unrolled(outputs, states, (2, 3, 10))
    assert ex.outputs[0].shape == (2, 3, 50)


def test_zoneout_cell_runs():
    cell = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(16, prefix="z_"), 0.5, 0.5)
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = _exec_unrolled(outputs, states, (2, 3, 16))
    assert ex.outputs[0].shape == (2, 3, 16)


def test_fused_unfused_equivalence():
    """FusedRNNCell (lax.scan RNN op) must numerically match the unrolled
    LSTMCell graph given identical weights — the reference checked cuDNN
    vs explicit unroll the same way."""
    T, N, I, H = 5, 3, 4, 6
    x = np.random.RandomState(0).uniform(-1, 1, (N, T, I)) \
        .astype(np.float32)

    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                prefix="lstm_", get_next_state=True)
    f_out, f_states = fused.unroll(T, inputs=mx.sym.Variable("data"),
                                   merge_outputs=True)
    f_ex = mx.sym.Group([f_out] + list(f_states)).simple_bind(
        ctx=mx.cpu(), grad_req="null", data=(N, T, I))
    rng = np.random.RandomState(1)
    pvec = rng.uniform(-0.5, 0.5,
                       f_ex.arg_dict["lstm_parameters"].shape) \
        .astype(np.float32)
    f_ex.arg_dict["lstm_parameters"][:] = pvec
    f_ex.arg_dict["data"][:] = x
    f_ex.forward(is_train=False)
    fused_out = f_ex.outputs[0].asnumpy()

    # unfuse → same weights via pack/unpack roundtrip
    from incubator_mxnet_tpu.ndarray import array as nd_array
    unfused = fused.unfuse()
    args = unfused.pack_weights(
        fused.unpack_weights({"lstm_parameters": nd_array(pvec)}))
    u_out, u_states = unfused.unroll(T, inputs=mx.sym.Variable("data"),
                                     merge_outputs=True)
    u_ex = u_out.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(N, T, I))
    for name in u_ex.arg_dict:
        if name == "data":
            u_ex.arg_dict[name][:] = x
        else:
            u_ex.arg_dict[name][:] = args[name].asnumpy()
    u_ex.forward(is_train=False)
    unfused_out = u_ex.outputs[0].asnumpy()
    np.testing.assert_allclose(fused_out, unfused_out, rtol=1e-4,
                               atol=1e-5)


def test_pack_unpack_roundtrip():
    from incubator_mxnet_tpu.ndarray import array as nd_array

    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="gru",
                                prefix="gru_")
    n = 0
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size
    n = rnn_param_size("gru", 2, 4, 6)
    pvec = np.arange(n, dtype=np.float32)
    unpacked = fused.unpack_weights({"gru_parameters": nd_array(pvec)})
    assert "gru_parameters" not in unpacked
    packed = fused.pack_weights(unpacked)
    np.testing.assert_allclose(packed["gru_parameters"].asnumpy(), pvec)


def test_encode_sentences_and_bucket_iter():
    sentences = [["a", "b", "c"], ["a", "c"], ["b", "c", "a", "b"],
                 ["a", "b"], ["c", "b"], ["a", "a", "b"]]
    coded, vocab = mx.rnn.encode_sentences(sentences, start_label=1)
    assert all(all(isinstance(i, int) for i in s) for s in coded)
    it = mx.rnn.BucketSentenceIter(coded, batch_size=2, buckets=[2, 3, 4],
                                   invalid_label=0)
    seen = 0
    for batch in it:
        assert batch.data[0].shape[0] == 2
        assert batch.data[0].shape[1] == batch.bucket_key
        seen += 1
    assert seen >= 2


def test_ptb_lstm_bucketing_trains():
    """BASELINE config 3 slice: tiny PTB-style LM through
    BucketingModule + fused LSTM."""
    rng = np.random.RandomState(0)
    vocab = 20
    sentences = [list(rng.randint(1, vocab, rng.randint(3, 9)))
                 for _ in range(64)]
    sentences = [[int(w) for w in s] for s in sentences]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8], invalid_label=0)
    from incubator_mxnet_tpu.models.lstm_ptb import lstm_ptb_sym_gen
    sym_gen = lstm_ptb_sym_gen(num_embed=16, num_hidden=16,
                               num_layers=1, vocab_size=vocab,
                               fused=True)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            eval_metric="Perplexity",
            initializer=mx.initializer.Xavier())
    # forward once more; perplexity should be < vocab (i.e. learned >
    # uniform)
    score = mod.score(it, mx.metric.Perplexity(ignore_label=None))
    assert score[0][1] < vocab, score


def test_rnn_checkpoint_roundtrip(tmp_path):
    from incubator_mxnet_tpu.ndarray import array as nd_array
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    cell = mx.rnn.FusedRNNCell(6, num_layers=1, mode="lstm",
                               prefix="lstm_")
    out, _ = cell.unroll(3, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    n = rnn_param_size("lstm", 1, 4, 6)
    arg = {"lstm_parameters": nd_array(
        np.random.RandomState(0).randn(n).astype(np.float32))}
    prefix = str(tmp_path / "rnncp")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 1, out, arg, {})
    sym2, arg2, aux2 = mx.rnn.load_rnn_checkpoint(cell, prefix, 1)
    np.testing.assert_allclose(arg2["lstm_parameters"].asnumpy(),
                               arg["lstm_parameters"].asnumpy(),
                               rtol=1e-6)
