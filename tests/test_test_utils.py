"""Tests for mx.test_utils — the numeric-check harness itself.

Mirrors how the reference suite uses ``test_utils`` in
``tests/python/unittest/test_operator.py``: finite-difference grads and
numpy-oracle forward/backward checks on small symbols.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import test_utils as tu


def test_assert_almost_equal_reports_index():
    a = np.zeros((2, 3))
    b = np.zeros((2, 3))
    b[1, 2] = 1.0
    with pytest.raises(AssertionError) as e:
        tu.assert_almost_equal(a, b, rtol=1e-5, atol=1e-8)
    assert "(1, 2)" in str(e.value)
    tu.assert_almost_equal(a, a)


def test_rand_ndarray_and_same():
    arr = tu.rand_ndarray((3, 4))
    assert arr.shape == (3, 4)
    assert tu.same(arr, arr.asnumpy())


def test_check_symbolic_forward_mul():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a * b + a
    x = np.random.rand(3, 4).astype(np.float32)
    y = np.random.rand(3, 4).astype(np.float32)
    tu.check_symbolic_forward(out, {"a": x, "b": y}, [x * y + x],
                              rtol=1e-5)


def test_check_symbolic_backward_mul():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a * b
    x = np.random.rand(3, 4).astype(np.float32)
    y = np.random.rand(3, 4).astype(np.float32)
    og = np.random.rand(3, 4).astype(np.float32)
    tu.check_symbolic_backward(out, {"a": x, "b": y}, [og],
                               {"a": og * y, "b": og * x}, rtol=1e-5)


def test_check_symbolic_backward_add_req():
    a = mx.sym.Variable("a")
    out = a * 3.0
    x = np.random.rand(2, 2).astype(np.float32)
    og = np.ones((2, 2), np.float32)
    # grad_req='add' must accumulate onto the seeded grad buffer
    tu.check_symbolic_backward(out, {"a": x}, [og], {"a": og * 3.0},
                               grad_req="add", rtol=1e-5)


def test_check_numeric_gradient_dense():
    np.random.seed(7)
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    out = mx.sym.FullyConnected(data, weight=w, no_bias=True, num_hidden=3,
                                name="fc")
    tu.check_numeric_gradient(
        out, {"data": np.random.rand(2, 4).astype(np.float32),
              "w": np.random.rand(3, 4).astype(np.float32)},
        numeric_eps=1e-3, rtol=5e-2)


def test_check_numeric_gradient_nonlinear():
    np.random.seed(11)
    x = mx.sym.Variable("x")
    out = mx.sym.tanh(x)
    tu.check_numeric_gradient(
        out, {"x": np.random.uniform(-1, 1, (3, 3)).astype(np.float32)},
        numeric_eps=1e-3, rtol=5e-2)


def test_check_consistency_dtype():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    tu.check_consistency(out, dtypes=(np.float32, np.float16),
                         shapes={"data": (2, 8)})


def test_simple_forward():
    x = mx.sym.Variable("x")
    out = mx.sym.relu(x)
    val = np.array([[-1.0, 2.0]], np.float32)
    got = tu.simple_forward(out, x=val)
    np.testing.assert_allclose(got, np.maximum(val, 0))
