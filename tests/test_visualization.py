"""print_summary / plot_network over a small conv net (reference
``python/mxnet/visualization.py`` behavior)."""
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import visualization as viz


def _net():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    a = mx.sym.Activation(c, act_type="relu", name="relu1")
    p = mx.sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool1")
    f = mx.sym.Flatten(p, name="flat")
    fc = mx.sym.FullyConnected(f, num_hidden=10, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def test_print_summary(capsys):
    viz.print_summary(_net(), shape={"data": (1, 3, 16, 16)})
    out = capsys.readouterr().out
    assert "conv1" in out and "fc1" in out
    assert "Total params" in out


def test_plot_network_dot():
    g = viz.plot_network(_net(), shape={"data": (1, 3, 16, 16)})
    src = g if isinstance(g, str) else "\n".join(g.body)
    assert "conv1" in src and '"conv1" -> "relu1"' in src
    # weight/bias variables hidden by default
    assert "conv1_weight" not in src
