"""Detection op tests vs independent numpy oracles.

Oracles re-implement the reference CPU kernels
(``src/operator/contrib/multibox_{prior,target,detection}.cc``,
``src/operator/roi_pooling.cc``, ``src/operator/contrib/proposal.cc``)
directly in numpy/python so the XLA programs are checked numerically, the
test philosophy of ``tests/python/unittest/test_operator.py``.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import ndarray as nd


# ---------------------------------------------------------------------------
# numpy oracles
# ---------------------------------------------------------------------------


def np_multibox_prior(h, w, sizes, ratios, clip=False, steps=(-1, -1),
                      offsets=(0.5, 0.5)):
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    out = []
    for r in range(h):
        cy = (r + offsets[0]) * step_y
        for c in range(w):
            cx = (c + offsets[1]) * step_x
            for s in sizes:
                out.append([cx - s / 2, cy - s / 2, cx + s / 2, cy + s / 2])
            for ratio in ratios[1:]:
                sr = np.sqrt(ratio)
                ww, hh = sizes[0] * sr / 2, sizes[0] / sr / 2
                out.append([cx - ww, cy - hh, cx + ww, cy + hh])
    out = np.asarray(out, np.float32)
    if clip:
        out = np.clip(out, 0, 1)
    return out[None]


def np_iou(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    i = iw * ih
    u = ((a[2] - a[0]) * (a[3] - a[1])
         + (b[2] - b[0]) * (b[3] - b[1]) - i)
    return 0.0 if u <= 0 else i / u


def np_multibox_target(anchors, labels, cls_preds, overlap_threshold=0.5,
                       ignore_label=-1.0, negative_mining_ratio=-1.0,
                       negative_mining_thresh=0.5,
                       variances=(0.1, 0.1, 0.2, 0.2)):
    B, L, _ = labels.shape
    N = anchors.shape[0]
    loc_t = np.zeros((B, N * 4), np.float32)
    loc_m = np.zeros((B, N * 4), np.float32)
    cls_t = np.full((B, N), ignore_label, np.float32)
    for nb in range(B):
        num_valid = 0
        for i in range(L):
            if labels[nb, i, 0] == -1:
                break
            num_valid += 1
        if num_valid == 0:
            continue
        ov = np.zeros((N, num_valid))
        for j in range(N):
            for k in range(num_valid):
                ov[j, k] = np_iou(anchors[j], labels[nb, k, 1:5])
        gt_flags = [False] * num_valid
        match = [(-1.0, -1)] * N
        anchor_flags = [-1] * N
        num_positive = 0
        while not all(gt_flags):
            best_a, best_g, best = -1, -1, 1e-6
            for j in range(N):
                if anchor_flags[j] == 1:
                    continue
                for k in range(num_valid):
                    if gt_flags[k]:
                        continue
                    if ov[j, k] > best:
                        best_a, best_g, best = j, k, ov[j, k]
            if best_a == -1:
                break
            match[best_a] = (best, best_g)
            gt_flags[best_g] = True
            anchor_flags[best_a] = 1
            num_positive += 1
        if overlap_threshold > 0:
            for j in range(N):
                if anchor_flags[j] == 1:
                    continue
                best_g = int(np.argmax(ov[j]))
                match[j] = (ov[j, best_g], best_g)
                if ov[j, best_g] > overlap_threshold:
                    anchor_flags[j] = 1
                    gt_flags[best_g] = True
                    num_positive += 1
        if negative_mining_ratio > 0:
            num_neg = int(num_positive * negative_mining_ratio)
            num_neg = min(num_neg, N - num_positive)
            if num_neg > 0:
                cand = []
                for j in range(N):
                    if anchor_flags[j] == 1:
                        continue
                    if match[j][0] < 0:
                        best_g = int(np.argmax(ov[j]))
                        match[j] = (ov[j, best_g], best_g)
                    if match[j][0] < negative_mining_thresh:
                        logits = cls_preds[nb, :, j]
                        p = np.exp(logits - logits.max())
                        prob = p[0] / p.sum()
                        cand.append((-prob, j))
                cand.sort(key=lambda t: t[0], reverse=True)
                for _, j in cand[:num_neg]:
                    anchor_flags[j] = 0
        else:
            for j in range(N):
                if anchor_flags[j] != 1:
                    anchor_flags[j] = 0
        for i in range(N):
            if anchor_flags[i] == 1:
                g = match[i][1]
                cls_t[nb, i] = labels[nb, g, 0] + 1
                loc_m[nb, i * 4:i * 4 + 4] = 1
                a = anchors[i]
                l = labels[nb, g, 1:5]
                aw, ah = a[2] - a[0], a[3] - a[1]
                ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
                gw, gh = l[2] - l[0], l[3] - l[1]
                gx, gy = (l[0] + l[2]) / 2, (l[1] + l[3]) / 2
                vx, vy, vw, vh = variances
                loc_t[nb, i * 4:i * 4 + 4] = [
                    (gx - ax) / aw / vx, (gy - ay) / ah / vy,
                    np.log(gw / aw) / vw, np.log(gh / ah) / vh]
            elif anchor_flags[i] == 0:
                cls_t[nb, i] = 0
    return loc_t, loc_m, cls_t


def np_multibox_detection(cls_prob, loc_pred, anchors, threshold=0.01,
                          clip=True, nms_threshold=0.5, force_suppress=False,
                          variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    B, C, N = cls_prob.shape
    out = np.full((B, N, 6), -1.0, np.float32)
    vx, vy, vw, vh = variances
    for nb in range(B):
        rows = []
        for i in range(N):
            score, cid = -1.0, 0
            for j in range(1, C):
                if cls_prob[nb, j, i] > score:
                    score, cid = cls_prob[nb, j, i], j
            if cid > 0 and score < threshold:
                cid = 0
            if cid > 0:
                a = anchors[i]
                p = loc_pred[nb, i * 4:i * 4 + 4]
                aw, ah = a[2] - a[0], a[3] - a[1]
                ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
                ox = p[0] * vx * aw + ax
                oy = p[1] * vy * ah + ay
                ow = np.exp(p[2] * vw) * aw / 2
                oh = np.exp(p[3] * vh) * ah / 2
                box = [ox - ow, oy - oh, ox + ow, oy + oh]
                if clip:
                    box = [min(1.0, max(0.0, v)) for v in box]
                rows.append([cid - 1, score] + box)
        rows.sort(key=lambda r: -r[1])
        if nms_topk > 0:
            rows = rows[:nms_topk]
        if 0 < nms_threshold <= 1:
            for i in range(len(rows)):
                if rows[i][0] < 0:
                    continue
                for j in range(i + 1, len(rows)):
                    if rows[j][0] < 0:
                        continue
                    if force_suppress or rows[i][0] == rows[j][0]:
                        if np_iou(rows[i][2:], rows[j][2:]) >= nms_threshold:
                            rows[j][0] = -1
        for i, r in enumerate(rows):
            out[nb, i] = r
    return out


def np_roi_pooling(data, rois, pooled_size, spatial_scale):
    B, C, H, W = data.shape
    ph, pw = pooled_size
    R = rois.shape[0]
    out = np.zeros((R, C, ph, pw), np.float32)
    for n in range(R):
        b = int(rois[n, 0])
        x1 = int(round(rois[n, 1] * spatial_scale))
        y1 = int(round(rois[n, 2] * spatial_scale))
        x2 = int(round(rois[n, 3] * spatial_scale))
        y2 = int(round(rois[n, 4] * spatial_scale))
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        bh, bw = rh / ph, rw / pw
        for c in range(C):
            for i in range(ph):
                for j in range(pw):
                    hs = min(max(int(np.floor(i * bh)) + y1, 0), H)
                    he = min(max(int(np.ceil((i + 1) * bh)) + y1, 0), H)
                    ws = min(max(int(np.floor(j * bw)) + x1, 0), W)
                    we = min(max(int(np.ceil((j + 1) * bw)) + x1, 0), W)
                    if he <= hs or we <= ws:
                        out[n, c, i, j] = 0
                    else:
                        out[n, c, i, j] = data[b, c, hs:he, ws:we].max()
    return out


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------


def test_multibox_prior():
    rng = np.random.RandomState(0)
    data = rng.rand(1, 3, 4, 6).astype(np.float32)
    sizes, ratios = (0.4, 0.8), (1.0, 2.0, 0.5)
    # contrib ndarray namespace (mx.contrib.nd.MultiBoxPrior)
    got = mx.contrib.nd.MultiBoxPrior(nd.array(data), sizes=str(sizes),
                                      ratios=str(ratios), clip="1")
    want = np_multibox_prior(4, 6, sizes, ratios, clip=True)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5, atol=1e-6)


def test_multibox_prior_steps_offsets():
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(1)
    data = rng.rand(2, 8, 5, 5).astype(np.float32)
    op = get_op("_contrib_MultiBoxPrior")
    outs, _ = op.apply([data], {"sizes": "(0.3,)", "ratios": "(1, 3)",
                                "steps": "(0.1, 0.2)",
                                "offsets": "(0.2, 0.7)"})
    want = np_multibox_prior(5, 5, (0.3,), (1, 3), steps=(0.1, 0.2),
                             offsets=(0.2, 0.7))
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5,
                               atol=1e-6)


def _rand_labels(rng, B, L, num_valid_per_batch):
    labels = np.full((B, L, 5), -1.0, np.float32)
    for b in range(B):
        for i in range(num_valid_per_batch[b]):
            cls = rng.randint(0, 3)
            x1, y1 = rng.uniform(0, 0.6, 2)
            w, h = rng.uniform(0.1, 0.35, 2)
            labels[b, i] = [cls, x1, y1, min(x1 + w, 1.0), min(y1 + h, 1.0)]
    return labels


@pytest.mark.parametrize("mining", [-1.0, 3.0])
def test_multibox_target(mining):
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(42)
    anchors = np_multibox_prior(4, 4, (0.3, 0.6), (1, 2, 0.5))[0]
    N = anchors.shape[0]
    B, L, C = 3, 6, 4
    labels = _rand_labels(rng, B, L, [2, 0, 4])
    cls_preds = rng.randn(B, C, N).astype(np.float32)
    attrs = {"overlap_threshold": "0.5",
             "negative_mining_ratio": str(mining),
             "negative_mining_thresh": "0.5"}
    op = get_op("_contrib_MultiBoxTarget")
    outs, _ = op.apply([anchors[None], labels, cls_preds], attrs)
    want = np_multibox_target(anchors, labels, cls_preds,
                              negative_mining_ratio=mining)
    np.testing.assert_allclose(np.asarray(outs[0]), want[0], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), want[1], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[2]), want[2], rtol=1e-5)


@pytest.mark.parametrize("force", [False, True])
def test_multibox_detection(force):
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(7)
    anchors = np_multibox_prior(3, 3, (0.4,), (1, 2))[0]
    N = anchors.shape[0]
    B, C = 2, 3
    cls_prob = rng.rand(B, C, N).astype(np.float32)
    cls_prob /= cls_prob.sum(axis=1, keepdims=True)
    loc_pred = (rng.randn(B, N * 4) * 0.2).astype(np.float32)
    attrs = {"threshold": "0.2", "nms_threshold": "0.45",
             "force_suppress": "1" if force else "0"}
    op = get_op("_contrib_MultiBoxDetection")
    outs, _ = op.apply([cls_prob, loc_pred, anchors[None]], attrs)
    want = np_multibox_detection(cls_prob, loc_pred, anchors, threshold=0.2,
                                 nms_threshold=0.45, force_suppress=force)
    got = np.asarray(outs[0])
    # rows are sorted by score; ties could reorder, so compare row sets of
    # surviving detections then the full array
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_roi_pooling_forward():
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(3)
    data = rng.randn(2, 3, 12, 16).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7],
                     [1, 2, 2, 15, 11],
                     [0, 4, 4, 4, 4],
                     [1, 0, 3, 14, 10]], np.float32)
    op = get_op("ROIPooling")
    outs, _ = op.apply([data, rois],
                       {"pooled_size": "(3, 3)", "spatial_scale": "1.0"})
    want = np_roi_pooling(data, rois, (3, 3), 1.0)
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5)


def test_roi_pooling_spatial_scale_and_grad():
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(5)
    data = rng.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 15, 15]], np.float32)
    op = get_op("ROIPooling")
    attrs = {"pooled_size": "(2, 2)", "spatial_scale": "0.5"}
    outs, _ = op.apply([data, rois], attrs)
    want = np_roi_pooling(data, rois, (2, 2), 0.5)
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5)

    # grad flows to argmax elements only
    def f(x):
        o, _ = op.apply([x, jnp.asarray(rois)], attrs)
        return jnp.sum(o[0])

    g = np.asarray(jax.grad(f)(jnp.asarray(data)))
    assert g.shape == data.shape
    # each of the 2x2x2 output bins contributes gradient 1 to its argmax
    assert g.sum() == pytest.approx(8.0)
    assert ((g == 0) | (g == 1)).all() or g.max() <= 2.0


def test_roi_pooling_symbol_bind():
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    pooled = mx.sym.ROIPooling(data=data, rois=rois, pooled_size=(4, 4),
                               spatial_scale=0.0625)
    arg_shapes, out_shapes, _ = pooled.infer_shape(
        data=(1, 64, 32, 32), rois=(8, 5))
    assert out_shapes[0] == (8, 64, 4, 4)


def test_proposal():
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(11)
    A, fh, fw = 3, 4, 4
    cls_prob = rng.rand(1, 2 * A, fh, fw).astype(np.float32)
    bbox_pred = (rng.randn(1, 4 * A, fh, fw) * 0.1).astype(np.float32)
    im_info = np.array([[64, 64, 1.0]], np.float32)
    attrs = {"feature_stride": "16", "scales": "(8,)",
             "ratios": "(0.5, 1, 2)", "rpn_pre_nms_top_n": "12",
             "rpn_post_nms_top_n": "4", "threshold": "0.7",
             "rpn_min_size": "4", "output_score": "1"}
    op = get_op("_contrib_Proposal")
    outs, _ = op.apply([cls_prob, bbox_pred, im_info], attrs)
    rois, scores = np.asarray(outs[0]), np.asarray(outs[1])
    assert rois.shape == (4, 5)
    assert scores.shape == (4, 1)
    assert (rois[:, 0] == 0).all()
    # boxes are inside the (clipped) image
    assert (rois[:, 1] >= 0).all() and (rois[:, 3] <= 63).all()
    assert (rois[:, 2] >= 0).all() and (rois[:, 4] <= 63).all()
    # kept proposals are sorted by score descending (greedy NMS keep order)
    real = scores[:, 0][scores[:, 0] > 0]
    assert (np.diff(real) <= 1e-6).all()


def test_multi_proposal_batch_image_index():
    """Batch > 1 MultiProposal fills rois column 0 with the per-image
    index (multi_proposal.cu PrepareOutput: out[index*5] = image_index) —
    ROIPooling uses it as the batch index downstream."""
    from incubator_mxnet_tpu.ops.registry import get_op
    rng = np.random.RandomState(7)
    B, A, fh, fw = 3, 3, 4, 4
    post_n = 4
    cls_prob = rng.rand(B, 2 * A, fh, fw).astype(np.float32)
    bbox_pred = (rng.randn(B, 4 * A, fh, fw) * 0.1).astype(np.float32)
    im_info = np.tile(np.array([[64, 64, 1.0]], np.float32), (B, 1))
    attrs = {"feature_stride": "16", "scales": "(8,)",
             "ratios": "(0.5, 1, 2)", "rpn_pre_nms_top_n": "12",
             "rpn_post_nms_top_n": str(post_n), "threshold": "0.7",
             "rpn_min_size": "4"}
    op = get_op("_contrib_MultiProposal")
    outs, _ = op.apply([cls_prob, bbox_pred, im_info], attrs)
    rois = np.asarray(outs[0])
    assert rois.shape == (B * post_n, 5)
    expect = np.repeat(np.arange(B), post_n)
    np.testing.assert_array_equal(rois[:, 0], expect)


def test_multibox_symbolic_compose():
    """The three SSD ops compose into a symbolic graph and infer shapes
    (reference: example/ssd usage of the contrib symbols)."""
    data = mx.sym.Variable("data")
    anchors = mx.contrib.sym.MultiBoxPrior(data, sizes="(0.2, 0.4)",
                                           ratios="(1, 2, 0.5)")
    _, out_shapes, _ = anchors.infer_shape(data=(2, 16, 8, 8))
    assert out_shapes[0] == (1, 8 * 8 * 4, 4)

    label = mx.sym.Variable("label")
    cls_pred = mx.sym.Variable("cls_pred")
    tgt = mx.contrib.sym.MultiBoxTarget(anchors, label, cls_pred)
    _, t_shapes, _ = tgt.infer_shape(data=(2, 16, 8, 8), label=(2, 4, 5),
                                     cls_pred=(2, 3, 256))
    assert t_shapes == [(2, 1024), (2, 1024), (2, 256)]
