"""Gluon tests — reference ``tests/python/unittest/test_gluon*.py``
semantics."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu import autograd as ag
from incubator_mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(5, 4))
    p.initialize(init=mx.init.Xavier(), ctx=[mx.cpu(0)])
    assert p.data().shape == (5, 4)
    assert p.grad().shape == (5, 4)
    p.zero_grad()
    np.testing.assert_allclose(p.grad().asnumpy(), np.zeros((5, 4)))


def test_parameter_dict_get_shared():
    params = gluon.ParameterDict("net_")
    a = params.get("w", shape=(2, 2))
    b = params.get("w")
    assert a is b
    assert a.name == "net_w"


def test_dense_forward_and_deferred_init():
    layer = nn.Dense(8, in_units=4)
    layer.initialize()
    x = mx.nd.ones((2, 4))
    out = layer(x)
    assert out.shape == (2, 8)

    # deferred: in_units unknown until first forward
    layer2 = nn.Dense(3)
    layer2.initialize()
    out2 = layer2(mx.nd.ones((5, 7)))
    assert out2.shape == (5, 3)
    assert layer2.weight.shape == (3, 7)


def test_sequential_and_training():
    rng = np.random.RandomState(0)
    x = rng.randn(128, 10).astype(np.float32)
    w = rng.randn(10, 1).astype(np.float32)
    y = x.dot(w) + 0.1

    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="local")
    l2 = gluon.loss.L2Loss()

    losses = []
    for epoch in range(30):
        with ag.record():
            out = net(mx.nd.array(x))
            loss = l2(out, mx.nd.array(y))
        loss.backward()
        trainer.step(128)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_conv_block():
    layer = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    layer.initialize()
    out = layer(mx.nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 8, 8, 8)

    pool = nn.MaxPool2D(2, 2)
    assert pool(out).shape == (2, 8, 4, 4)

    gap = nn.GlobalAvgPool2D()
    assert gap(out).shape == (2, 8, 1, 1)


def test_batchnorm_block_updates_running_stats():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    x = mx.nd.array(np.random.randn(16, 4, 3, 3).astype(np.float32) * 3 + 1)
    with ag.record():
        out = layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0


def test_hybridize():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.ones((3, 7))
    out_imp = net(x).asnumpy()
    net.hybridize()
    out_hyb = net(x).asnumpy()
    np.testing.assert_allclose(out_imp, out_hyb, rtol=1e-5)


def test_losses():
    pred = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = mx.nd.array([[1.5, 2.5], [2.0, 5.0]])
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(
        l2, ((pred.asnumpy() - label.asnumpy()) ** 2 / 2).mean(1),
        rtol=1e-5)

    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(
        l1, np.abs(pred.asnumpy() - label.asnumpy()).mean(1), rtol=1e-5)

    logits = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    lab = mx.nd.array([0, 1, 2, 3])
    ce = gluon.loss.SoftmaxCrossEntropyLoss()(logits, lab).asnumpy()
    lp = np.log(np.exp(logits.asnumpy())
                / np.exp(logits.asnumpy()).sum(1, keepdims=True))
    expect = -lp[np.arange(4), [0, 1, 2, 3]]
    np.testing.assert_allclose(ce, expect, rtol=1e-4)


def test_save_load_params(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    _ = net(mx.nd.ones((1, 6)))
    fname = str(tmp_path / "net.params")
    net.save_params(fname)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4), nn.Dense(2))
    net2.load_params(fname)
    out1 = net(mx.nd.ones((1, 6))).asnumpy()
    out2 = net2(mx.nd.ones((1, 6))).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_dataset_dataloader():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.float32)
    ds = gluon.data.ArrayDataset(x, y)
    assert len(ds) == 10
    loader = gluon.data.DataLoader(ds, batch_size=3, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (3, 2)

    loader2 = gluon.data.DataLoader(ds, batch_size=3, shuffle=True,
                                    last_batch="discard")
    assert len(list(loader2)) == 3


def test_dataloader_workers_match_serial():
    x = np.arange(60, dtype=np.float32).reshape(30, 2)
    y = np.arange(30, dtype=np.float32)
    ds = gluon.data.ArrayDataset(x, y)
    serial = [(d.asnumpy(), l.asnumpy()) for d, l in
              gluon.data.DataLoader(ds, batch_size=4, last_batch="keep")]
    threaded = [(d.asnumpy(), l.asnumpy()) for d, l in
                gluon.data.DataLoader(ds, batch_size=4, last_batch="keep",
                                      num_workers=3)]
    assert len(serial) == len(threaded)
    for (d0, l0), (d1, l1) in zip(serial, threaded):
        np.testing.assert_array_equal(d0, d1)
        np.testing.assert_array_equal(l0, l1)


def test_dataloader_workers_overlap():
    import time

    class SlowDataset(gluon.data.Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, idx):
            time.sleep(0.01)
            return np.float32(idx)

    ds = SlowDataset()
    t0 = time.time()
    n0 = len(list(gluon.data.DataLoader(ds, batch_size=8, num_workers=0)))
    serial_t = time.time() - t0
    t0 = time.time()
    n4 = len(list(gluon.data.DataLoader(ds, batch_size=8, num_workers=4)))
    worker_t = time.time() - t0
    assert n0 == n4 == 4
    # 4 batches fetched by 4 workers concurrently; generous margin for CI
    assert worker_t < serial_t * 0.75, (serial_t, worker_t)


def test_vision_dataset_synthetic():
    ds = gluon.data.vision.MNIST(root="/nonexistent_mnist")
    img, label = ds[0]
    assert img.shape == (28, 28, 1)
    assert 0 <= label < 10


def test_split_and_load():
    data = mx.nd.arange(0, 16).reshape((8, 2))
    parts = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert parts[0].shape == (4, 2)
    np.testing.assert_allclose(
        np.concatenate([p.asnumpy() for p in parts]), data.asnumpy())


def test_clip_global_norm():
    arrays = [mx.nd.ones((2, 2)) * 3, mx.nd.ones((2,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-4


def test_lstm_cell_unroll():
    cell = gluon.rnn.LSTMCell(8, input_size=4)
    cell.initialize()
    x = mx.nd.ones((2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 8)
    assert states[0].shape == (2, 8)


def test_lstm_layer_forward_backward():
    layer = gluon.rnn.LSTM(16, num_layers=2, input_size=8)
    layer.initialize()
    x = mx.nd.array(np.random.randn(10, 4, 8).astype(np.float32))
    out = layer(x)
    assert out.shape == (10, 4, 16)

    # grads flow to per-layer params through the fused op
    params = layer.collect_params()
    with ag.record():
        out = layer(x)
        loss = mx.nd.sum(out * out)
    loss.backward()
    g = params[layer.prefix + "l0_i2h_weight"].grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_gru_bidirectional():
    layer = gluon.rnn.GRU(6, num_layers=1, bidirectional=True,
                          input_size=3)
    layer.initialize()
    out = layer(mx.nd.ones((7, 2, 3)))
    assert out.shape == (7, 2, 12)


def test_model_zoo_construct():
    for name in ["resnet18_v1", "resnet18_v2", "squeezenet1.1", "alexnet"]:
        net = gluon.model_zoo.get_model(name, classes=10)
        net.initialize(mx.init.Xavier())
        out = net(mx.nd.ones((1, 3, 224, 224)))
        assert out.shape == (1, 10), name


def test_model_zoo_densenet():
    net = gluon.model_zoo.get_model("densenet121", classes=10)
    net.initialize(mx.init.Xavier())
    out = net(mx.nd.ones((1, 3, 224, 224)))
    assert out.shape == (1, 10)


def test_model_zoo_inception_v3():
    # reference inception.py:Inception3 — 299x299 input
    net = gluon.model_zoo.get_model("inceptionv3", classes=10)
    net.initialize(mx.init.Xavier())
    out = net(mx.nd.ones((1, 3, 299, 299)))
    assert out.shape == (1, 10)


def test_image_record_and_folder_datasets(tmp_path):
    """ImageRecordDataset / ImageFolderDataset (reference
    gluon/data/vision.py:166,197) decode to (HWC image, label)."""
    cv2 = pytest.importorskip("cv2")
    import importlib.util
    import os

    _spec = importlib.util.spec_from_file_location(
        "tp_im2rec", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "im2rec.py"))
    im2rec = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(im2rec)

    rng = np.random.RandomState(0)
    root = tmp_path / "imgs"
    for cls in ("ant", "bee"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            cv2.imwrite(str(d / ("%d.jpg" % i)),
                        rng.randint(0, 255, (20, 24, 3)).astype(np.uint8))

    folder = mx.gluon.data.vision.ImageFolderDataset(str(root))
    assert folder.synsets == ["ant", "bee"]
    assert len(folder) == 6
    img, label = folder[4]
    assert img.shape == (20, 24, 3) and label == 1

    prefix = str(tmp_path / "pack")
    im2rec.main([prefix, str(root)])
    rec = mx.gluon.data.vision.ImageRecordDataset(prefix + ".rec")
    assert len(rec) == 6
    img, label = rec[0]
    assert img.shape == (20, 24, 3) and float(label) in (0.0, 1.0)
    # transform hook
    rec_t = mx.gluon.data.vision.ImageRecordDataset(
        prefix + ".rec",
        transform=lambda d, l: (d.astype("float32") / 255.0, l))
    img_t, _ = rec_t[0]
    assert img_t.dtype == np.float32 and float(img_t.asnumpy().max()) <= 1
    # feeds a DataLoader end-to-end — including THREADED workers, which
    # share the record handle (read_idx is lock-atomic)
    for workers in (0, 2):
        loader = mx.gluon.data.DataLoader(rec_t, batch_size=3,
                                          num_workers=workers)
        batches = list(loader)
        assert len(batches) == 2
        assert batches[0][0].shape == (3, 20, 24, 3)


def test_fused_softmax_ce_head_trains():
    """gluon FusedSoftmaxCEHead: numerics match log_softmax NLL on the
    same weight, and a tiny model trains through it."""
    import numpy as np

    from incubator_mxnet_tpu import autograd, gluon
    import incubator_mxnet_tpu as mx

    rng = np.random.RandomState(0)
    head = gluon.loss.FusedSoftmaxCEHead(vocab_size=7, in_units=8)
    head.initialize(mx.initializer.Xavier())
    x = mx.nd.array(rng.randn(10, 8).astype(np.float32))
    lab = mx.nd.array(rng.randint(0, 7, (10,)).astype(np.float32))
    loss = head(x, lab)
    w = head.head_weight.data().asnumpy()
    logits = x.asnumpy() @ w.T
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                 .sum(-1)) + logits.max(-1)
    nll = lse - logits[np.arange(10), lab.asnumpy().astype(int)]
    np.testing.assert_allclose(float(loss.asnumpy()), nll.mean(),
                               rtol=1e-5)

    # trains: loss drops with SGD on the head weight
    trainer = gluon.Trainer(head.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    first = None
    for i in range(30):
        with autograd.record():
            loss = head(x, lab)
        loss.backward()
        trainer.step(10)
        if first is None:
            first = float(loss.asnumpy())
    assert float(loss.asnumpy()) < 0.5 * first


def test_fused_softmax_ce_head_rejects_weighting():
    """weight/sample_weight would rescale only the reported loss value
    (the fused op's VJP ignores the incoming cotangent), silently NOT
    the gradients — both are rejected up front."""
    import numpy as np

    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.base import MXNetError
    import incubator_mxnet_tpu as mx

    with pytest.raises(MXNetError, match="weight"):
        gluon.loss.FusedSoftmaxCEHead(vocab_size=7, in_units=8,
                                      weight=0.5)

    head = gluon.loss.FusedSoftmaxCEHead(vocab_size=7, in_units=8)
    head.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(4, 8)
                    .astype(np.float32))
    lab = mx.nd.array(np.zeros(4, np.float32))
    with pytest.raises(MXNetError, match="sample_weight"):
        head(x, lab, mx.nd.ones((4,)))
