"""SSD detection stack end-to-end: ImageDetIter + SSD symbol fwd/bwd.

Reference analog: example/ssd training path (symbol_builder.get_symbol_train
driven by the det-record iterator, ``iter_image_det_recordio.cc``).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx

cv2 = pytest.importorskip("cv2")

# a small 3-scale SSD config so CPU tests stay fast (full ssd300 compiles
# minutes of VGG16 convs; the wiring under test is identical)
SMALL_CFG = dict(
    from_layers=["relu4_3", "relu7", ""],
    num_filters=[512, -1, 256],
    strides=[-1, -1, 2],
    pads=[-1, -1, 1],
    sizes=[[0.2, 0.272], [0.45, 0.55], [0.8, 0.9]],
    ratios=[[1, 2, 0.5]] * 3,
    normalizations=[20, -1, -1],
    steps=[],
)


def _det_label(objs):
    """[header_width=2, object_width=5, (id, x1, y1, x2, y2)*N]"""
    out = [2, 5]
    for o in objs:
        out.extend(o)
    return np.array(out, np.float32)


@pytest.fixture(scope="module")
def det_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("det_imgs")
    rng = np.random.RandomState(0)
    imglist = []
    for i in range(8):
        img = rng.randint(0, 255, (50, 60, 3)).astype(np.uint8)
        name = "img_%d.jpg" % i
        cv2.imwrite(str(root / name), img)
        n_obj = rng.randint(1, 4)
        objs = []
        for _ in range(n_obj):
            x1, y1 = rng.uniform(0, 0.5, 2)
            w, h = rng.uniform(0.2, 0.4, 2)
            objs.append([float(rng.randint(0, 3)), x1, y1,
                         min(x1 + w, 1.0), min(y1 + h, 1.0)])
        imglist.append([_det_label(objs), name])
    return str(root), imglist


def test_image_det_iter(det_dataset):
    root, imglist = det_dataset
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                               imglist=imglist, path_root=root)
    # label shape estimated as (max_objects, 5)
    assert it.label_shape[1] == 5
    max_obj = max((len(l[0]) - 2) // 5 for l in imglist)
    assert it.label_shape[0] == max_obj
    batch = it.next()
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    assert data.shape == (4, 3, 32, 32)
    assert label.shape == (4, max_obj, 5)
    # padded slots are -1, real slots have valid boxes
    for b in range(4):
        rows = label[b]
        valid = rows[:, 0] >= 0
        assert valid.any()
        assert (rows[~valid] == -1).all()
        vb = rows[valid]
        assert (vb[:, 3] > vb[:, 1]).all() and (vb[:, 4] > vb[:, 2]).all()


def test_image_det_iter_augment(det_dataset):
    root, imglist = det_dataset
    it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                               imglist=imglist, path_root=root,
                               rand_crop=0.5, rand_pad=0.5,
                               rand_mirror=True, mean=True, std=True)
    batch = it.next()
    label = batch.label[0].asnumpy()
    valid = label[label[:, :, 0] >= 0]
    assert (valid[:, 1:5] >= -1e-5).all() and (valid[:, 1:5] <= 1 + 1e-5).all()


def test_det_augmenter_flip():
    aug = mx.image.DetHorizontalFlipAug(p=1.0)
    src = np.arange(2 * 3 * 3).reshape(2, 3, 3).astype(np.uint8)
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    out, lab = aug(src, label)
    np.testing.assert_array_equal(out, src[:, ::-1])
    np.testing.assert_allclose(lab[0], [0, 0.6, 0.2, 0.9, 0.6], rtol=1e-6)


def test_det_random_crop_updates_labels():
    rng = np.random.RandomState(0)
    aug = mx.image.DetRandomCropAug(min_object_covered=0.1,
                                    area_range=(0.5, 1.0), max_attempts=20)
    src = rng.randint(0, 255, (40, 40, 3)).astype(np.uint8)
    label = np.array([[1, 0.3, 0.3, 0.7, 0.7]], np.float32)
    out, lab = aug(src, label)
    assert lab.shape[1] == 5
    assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()


def test_sync_label_shape(det_dataset):
    root, imglist = det_dataset
    it1 = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                                imglist=imglist[:4], path_root=root)
    it2 = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                                imglist=imglist[4:], path_root=root)
    it2 = it1.sync_label_shape(it2)
    assert it1.label_shape == it2.label_shape


@pytest.mark.slow
def test_ssd_train_forward_backward(det_dataset):
    """Small-config SSD: Module-free bind, one fwd/bwd, finite grads."""
    root, imglist = det_dataset
    net = mx.models.ssd_train(num_classes=3, **SMALL_CFG)
    batch, hw = 2, 64
    it = mx.image.ImageDetIter(batch_size=batch, data_shape=(3, hw, hw),
                               imglist=imglist, path_root=root)
    label_shape = (batch,) + it.label_shape
    ex = net.simple_bind(mx.cpu(), data=(batch, 3, hw, hw),
                         label=label_shape, grad_req="write")
    # init params
    init = mx.initializer.Xavier()
    for name, arr in ex.arg_dict.items():
        if name in ("data", "label"):
            continue
        init(mx.initializer.InitDesc(name), arr)
    b = it.next()
    ex.arg_dict["data"][:] = b.data[0]
    ex.arg_dict["label"][:] = b.label[0]
    ex.forward(is_train=True)
    ex.backward()
    outs = [o.asnumpy() for o in ex.outputs]
    # cls_prob (B, C+1, N), loc_loss, cls_label, det (B, N, 6)
    assert outs[0].shape[1] == 4
    assert outs[3].shape[2] == 6
    for o in outs:
        assert np.isfinite(o).all()
    g = ex.grad_dict["conv1_1_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_ssd_deploy_symbol_shapes():
    net = mx.models.ssd_deploy(num_classes=3, **SMALL_CFG)
    _, out_shapes, _ = net.infer_shape(data=(1, 3, 64, 64))
    assert len(out_shapes) == 1
    assert out_shapes[0][2] == 6
