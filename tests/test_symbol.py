"""Symbol + Executor tests — reference ``test_symbol.py`` /
``test_executor.py`` / ``test_infer_shape.py`` semantics."""
import numpy as np

import incubator_mxnet_tpu as mx


def _mlp_sym():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_compose_and_listing():
    net = _mlp_sym()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    internals = net.get_internals()
    assert "fc1_output" in internals.list_outputs()


def test_auto_naming():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4)
    assert fc.name.startswith("fullyconnected")
    fc2 = mx.sym.FullyConnected(data, num_hidden=4)
    assert fc2.name != fc.name


def test_infer_shape_mlp():
    net = _mlp_sym()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 100)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (10, 16)
    assert out_shapes == [(32, 10)]


def test_infer_shape_conv():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1))
    pool = mx.sym.Pooling(conv, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    arg_shapes, out_shapes, _ = pool.infer_shape(data=(2, 3, 28, 28))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d[conv.name + "_weight"] == (8, 3, 3, 3)
    assert out_shapes == [(2, 8, 14, 14)]


def test_infer_shape_batchnorm_aux():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn")
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    arg_shapes, _, aux_shapes = bn.infer_shape(data=(4, 3, 8, 8))
    assert aux_shapes == [(3,), (3,)]
    assert bn.list_arguments() == ["data", "bn_gamma", "bn_beta"]


def test_symbol_arithmetic():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a * 2 + b / 4 - 3
    ex = c.bind(mx.cpu(), args={"a": mx.nd.array([2.0]),
                                "b": mx.nd.array([8.0])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [2 * 2 + 8 / 4 - 3])


def test_simple_bind_forward_backward():
    np.random.seed(0)
    net = _mlp_sym()
    ex = net.simple_bind(ctx=mx.cpu(), data=(8, 20))
    # init params
    for name, arr in ex.arg_dict.items():
        if name.endswith("weight"):
            arr[:] = np.random.randn(*arr.shape).astype(np.float32) * 0.1
    x = np.random.randn(8, 20).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    ex.forward(is_train=True, data=x, softmax_label=y)
    out = ex.outputs[0].asnumpy()
    np.testing.assert_allclose(out.sum(axis=1), np.ones(8), rtol=1e-5)
    ex.backward()
    gw = ex.grad_dict["fc2_weight"].asnumpy()
    assert np.abs(gw).sum() > 0
    # data grad exists under write req
    assert ex.grad_dict["data"].shape == (8, 20)


def test_executor_grad_matches_finite_diff():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    loss = mx.sym.sum(mx.sym.square(data * w))
    ex = loss.bind(mx.cpu(),
                   args={"data": mx.nd.array([1.0, 2.0]),
                         "w": mx.nd.array([3.0, 4.0])},
                   args_grad={"w": mx.nd.zeros((2,))},
                   grad_req={"w": "write", "data": "null"})
    ex.forward(is_train=True)
    ex.backward()
    # d/dw sum((d*w)^2) = 2*d^2*w
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(),
                               2 * np.array([1., 4.]) * np.array([3., 4.]),
                               rtol=1e-5)


def test_grad_req_add():
    x_nd = mx.nd.array([2.0])
    g = mx.nd.zeros((1,))
    x = mx.sym.Variable("x")
    y = mx.sym.square(x)
    ex = y.bind(mx.cpu(), args={"x": x_nd}, args_grad={"x": g},
                grad_req={"x": "add"})
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(g.asnumpy(), [12.0])


def test_batchnorm_executor_aux_updates():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", momentum=0.5)
    ex = bn.simple_bind(ctx=mx.cpu(), data=(16, 4))
    x = np.random.randn(16, 4).astype(np.float32) * 3 + 2
    ex.aux_dict["bn_moving_var"][:] = 1.0
    ex.forward(is_train=True, data=x)
    _ = ex.outputs[0].asnumpy()
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert np.abs(mm).sum() > 0  # moved toward batch mean
    # eval mode uses moving stats
    ex.forward(is_train=False, data=x)
    out_eval = ex.outputs[0].asnumpy()
    assert out_eval.shape == (16, 4)


def test_save_load_json(tmp_path):
    net = _mlp_sym()
    fname = str(tmp_path / "net.json")
    net.save(fname)
    back = mx.sym.load(fname)
    assert back.list_arguments() == net.list_arguments()
    assert back.list_outputs() == net.list_outputs()
    # loaded symbol is executable
    ex = back.simple_bind(ctx=mx.cpu(), data=(2, 10))
    ex.forward(is_train=False,
               data=np.zeros((2, 10), dtype=np.float32))
    assert ex.outputs[0].shape == (2, 10)


def test_group_and_getitem():
    a = mx.sym.Variable("a")
    s1 = mx.sym.square(a, name="sq")
    s2 = mx.sym.sqrt(a, name="rt")
    g = mx.sym.Group([s1, s2])
    assert g.list_outputs() == ["sq_output", "rt_output"]
    ex = g.bind(mx.cpu(), args={"a": mx.nd.array([4.0])})
    outs = ex.forward()
    np.testing.assert_allclose(outs[0].asnumpy(), [16.0])
    np.testing.assert_allclose(outs[1].asnumpy(), [2.0])


def test_shared_exec_memory_sharing():
    # bucketing mechanism: shared_exec reuses param arrays
    net = _mlp_sym()
    ex1 = net.simple_bind(ctx=mx.cpu(), data=(4, 10))
    ex2 = net.simple_bind(ctx=mx.cpu(), data=(8, 10), shared_exec=ex1)
    assert ex2.arg_dict["fc1_weight"] is ex1.arg_dict["fc1_weight"]
    assert ex2.arg_dict["data"] is not ex1.arg_dict["data"]


def test_slice_channel_symbolic():
    data = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(data, num_outputs=2, axis=1, name="sc")
    assert len(parts.list_outputs()) == 2
    ex = parts.bind(mx.cpu(), args={"data": mx.nd.ones((2, 4))})
    outs = ex.forward()
    assert outs[0].shape == (2, 2)
