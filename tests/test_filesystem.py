"""Stream-URI filesystem layer (filesystem.py): remote record streams.

Reference capability: dmlc Stream URI dispatch — RecordIO straight
from S3/HDFS when built with USE_S3/USE_HDFS (make/config.mk:133-141).
Here: http(s) via a real local HTTP server with Range support; s3 via
a faked boto3 client (proves the ranged-GET code path without the
dependency); gating errors when backends are absent.
"""
import http.server
import os
import sys
import threading

import numpy as np
import pytest

from incubator_mxnet_tpu import recordio
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.filesystem import (HTTPRangeStream, open_uri,
                                            parse_uri)


def _make_pack(tmp_path, n=12):
    """A small indexed pack with varied record sizes."""
    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "pack")
    w = recordio.IndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    payloads = []
    for i in range(n):
        buf = rng.bytes(rng.randint(10, 4000))
        payloads.append(buf)
        w.write_idx(i, buf)
    w.close()
    return prefix, payloads


class _RangeHandler(http.server.SimpleHTTPRequestHandler):
    """SimpleHTTPRequestHandler with just enough Range support
    (stdlib's handler ignores Range, which the stream requires)."""

    def log_message(self, *a):
        pass

    def do_GET(self):
        path = self.translate_path(self.path)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self.send_error(404)
            return
        rng_h = self.headers.get("Range")
        if rng_h:
            spec = rng_h.split("=", 1)[1]
            lo, hi = spec.split("-")
            lo, hi = int(lo), int(hi)
            body = data[lo:hi + 1]
            self.send_response(206)
        else:
            body = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def http_root(tmp_path):
    prefix, payloads = _make_pack(tmp_path)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                          _RangeHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield "http://127.0.0.1:%d" % srv.server_port, payloads
    finally:
        srv.shutdown()
        os.chdir(cwd)


def test_parse_and_local_passthrough(tmp_path):
    assert parse_uri("s3://b/k/x.rec") == ("s3", "b/k/x.rec")
    assert parse_uri("/a/b.rec") == ("", "/a/b.rec")
    p = tmp_path / "f.bin"
    with open_uri(str(p), "wb") as f:
        f.write(b"xyz")
    with open_uri("file://" + str(p), "rb") as f:
        assert f.read() == b"xyz"


def test_http_range_stream_reads_and_seeks(http_root):
    base, _ = http_root
    url = base + "/pack.rec"
    s = HTTPRangeStream(url)
    with open("pack.rec", "rb") as f:
        ref = f.read()
    assert s.size == len(ref)
    assert s.read(100) == ref[:100]
    s.seek(-64, 2)
    assert s.read() == ref[-64:]
    s.seek(1234)
    assert s.read(4096) == ref[1234:1234 + 4096]


def test_recordio_over_http(http_root):
    """MXRecordIO + IndexedRecordIO read a remote pack record-for-record
    — incl. seeks through the remote .idx sidecar and the no-sidecar
    framing rescan over the range stream."""
    base, payloads = http_root
    r = recordio.MXRecordIO(base + "/pack.rec", "r")
    for want in payloads:
        assert r.read() == want
    assert r.read() is None
    r.close()

    idx = recordio.IndexedRecordIO(base + "/pack.idx",
                                   base + "/pack.rec", "r")
    assert idx.read_idx(7) == payloads[7]
    assert idx.read_idx(2) == payloads[2]
    idx.close()

    # no .idx: the index rebuilds by scanning the remote framing
    idx2 = recordio.IndexedRecordIO(base + "/nope.idx",
                                    base + "/pack.rec", "r")
    assert idx2.read_idx(11) == payloads[11]
    idx2.close()


def test_remote_write_and_unknown_scheme_raise(http_root):
    base, _ = http_root
    with pytest.raises(MXNetError, match="read-only"):
        recordio.MXRecordIO(base + "/out.rec", "w")
    with pytest.raises(MXNetError, match="scheme"):
        open_uri("ftp://host/x.rec")


def test_s3_stream_via_faked_boto3(tmp_path, monkeypatch):
    """The s3:// path issues HEAD + ranged GETs; a faked boto3 proves
    the protocol without the dependency, and its absence raises the
    gating error (the reference's USE_S3 gate, at runtime)."""
    prefix, payloads = _make_pack(tmp_path)
    with open(prefix + ".rec", "rb") as f:
        blob = f.read()

    class _Body:
        def __init__(self, b):
            self._b = b

        def read(self):
            return self._b

    class _Client:
        def head_object(self, Bucket, Key):
            assert (Bucket, Key) == ("mybucket", "packs/pack.rec")
            return {"ContentLength": len(blob)}

        def get_object(self, Bucket, Key, Range):
            lo, hi = Range.split("=")[1].split("-")
            return {"Body": _Body(blob[int(lo):int(hi) + 1])}

    class _FakeBoto3:
        @staticmethod
        def client(name):
            assert name == "s3"
            return _Client()

    monkeypatch.setitem(sys.modules, "boto3", _FakeBoto3)
    r = recordio.MXRecordIO("s3://mybucket/packs/pack.rec", "r")
    for want in payloads:
        assert r.read() == want
    r.close()

    monkeypatch.setitem(sys.modules, "boto3", None)  # import -> error
    with pytest.raises(MXNetError, match="boto3"):
        open_uri("s3://mybucket/packs/pack.rec")


def test_image_record_iter_over_http(tmp_path, http_root):
    """End-to-end: ImageRecordIter trains from an http:// pack URI —
    the reference's 'read ImageNet straight from S3' capability row."""
    cv2 = pytest.importorskip("cv2")
    import incubator_mxnet_tpu as mx

    base, _ = http_root
    # build a tiny image pack next to the served dir
    rng = np.random.RandomState(1)
    w = recordio.IndexedRecordIO("imgs.idx", "imgs.rec", "w")
    for i in range(8):
        img = rng.randint(0, 255, (40, 40, 3)).astype(np.uint8)
        ok, enc = cv2.imencode(".jpg", img)
        hdr = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack(hdr, enc.tobytes()))
    w.close()
    it = mx.io.ImageRecordIter(
        path_imgrec=base + "/imgs.rec", path_imgidx=base + "/imgs.idx",
        data_shape=(3, 32, 32), batch_size=4, rand_crop=True,
        shuffle=True, preprocess_threads=1)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 32, 32)


def test_nd_load_over_http(tmp_path, http_root):
    """mx.nd.load reads a .params blob from a remote URI (the
    reference's dmlc-Stream checkpoint-from-S3 capability row)."""
    import incubator_mxnet_tpu as mx

    d = {"w": mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4)),
         "b": mx.nd.ones((5,))}
    mx.nd.save("weights.params", d)  # saved into the served dir
    base, _ = http_root
    back = mx.nd.load(base + "/weights.params")
    assert set(back) == {"w", "b"}
    np.testing.assert_array_equal(back["w"].asnumpy(),
                                  d["w"].asnumpy())
