"""Recompute (remat) policy — MXNET_BACKWARD_DO_MIRROR parity.

Reference: ``src/executor/graph_executor.cc:215-273`` (mirror pass) and
``docs/how_to/env_var.md:89-94``.  The TPU redesign lives in
``lowering.py``: ``'mirror'`` = one ``jax.checkpoint`` saving only
matmul/conv-family outputs; int K = K checkpointed graph segments.
Remat must never change numerics — only the memory/recompute profile.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.lowering import lower_symbol, resolve_remat


def _conv_bn_net():
    d = mx.sym.Variable("data")
    x = d
    for i in range(2):
        # no_bias: under BatchNorm a conv bias is analytically zero-grad,
        # so its "gradient" is pure float noise — useless to compare
        x = mx.sym.Convolution(x, num_filter=8, kernel=(3, 3),
                               pad=(1, 1), no_bias=True,
                               name="conv%d" % i)
        x = mx.sym.BatchNorm(x, name="bn%d" % i)
        x = mx.sym.Activation(x, act_type="relu", name="relu%d" % i)
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool")
    x = mx.sym.FullyConnected(x, num_hidden=5, name="fc")
    return mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _grads(net, shapes, args, aux, remat):
    fwd = lower_symbol(net, is_train=True, remat=remat)
    key = jax.random.PRNGKey(0)

    def run(a):
        outs, new_aux = fwd(a, aux, key)
        return sum(jnp.sum(o) for o in outs), new_aux

    (loss, new_aux), grads = jax.jit(
        lambda a: jax.value_and_grad(run, has_aux=True)(a))(args)
    return loss, grads, new_aux


@pytest.mark.parametrize("remat", ["mirror", 2, 5])
def test_remat_is_numerically_invisible_conv_bn(remat):
    """Gradients AND the threaded BN aux updates match under every remat
    mode (conv/BN exercises aux write-back across segment boundaries).
    Tolerance is f32-recompute-level, not bitwise: XLA may fuse the
    rematerialized forward differently (observed 1e-4 rel on 1/216
    conv-weight grad elements on CPU), while a genuine remat bug — a
    dropped segment, stale aux — shows up at O(1)."""
    net = _conv_bn_net()
    shapes = dict(data=(2, 3, 8, 8), softmax_label=(2,))
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    args = {n: jnp.asarray(rng.uniform(-0.3, 0.3, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    args["softmax_label"] = jnp.asarray(
        rng.randint(0, 5, (2,)).astype(np.float32))
    aux = {n: jnp.ones(s) if n.endswith("var") else jnp.zeros(s)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)}

    loss0, g0, aux0 = _grads(net, shapes, args, aux, None)
    loss1, g1, aux1 = _grads(net, shapes, args, aux, remat)
    np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)
    for n in g0:
        np.testing.assert_allclose(np.asarray(g0[n]), np.asarray(g1[n]),
                                   rtol=1e-4, atol=1e-6, err_msg=n)
    for n in aux0:
        np.testing.assert_allclose(np.asarray(aux0[n]),
                                   np.asarray(aux1[n]),
                                   rtol=1e-6, err_msg=n)


@pytest.mark.parametrize("remat", ["mirror", 3])
def test_remat_is_numerically_invisible_fused_lm(remat):
    """The fused-head transformer (custom_vjp loss inside the
    checkpointed region) gives identical gradients under remat."""
    net = mx.models.transformer_lm(vocab_size=17, embed=16, heads=2,
                                   num_layers=3, seq_len=8,
                                   batch_size=2, head="fused")
    shapes = dict(data=(2, 8), softmax_label=(2, 8))
    arg_shapes, _, _ = net.infer_shape(**shapes)
    rng = np.random.RandomState(1)
    args = {n: jnp.asarray(rng.uniform(-0.2, 0.2, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    args["data"] = jnp.asarray(
        rng.randint(0, 17, (2, 8)).astype(np.float32))
    args["softmax_label"] = jnp.asarray(
        rng.randint(0, 17, (2, 8)).astype(np.float32))

    _, g0, _ = _grads(net, shapes, args, {}, None)
    _, g1, _ = _grads(net, shapes, args, {}, remat)
    for n in g0:
        np.testing.assert_allclose(np.asarray(g0[n]), np.asarray(g1[n]),
                                   rtol=1e-5, atol=1e-7, err_msg=n)


def test_remat_segments_reduce_saved_residuals():
    """A deep stack under K segments saves only boundary activations:
    the forward→backward residual footprint (what lives across the
    fwd/bwd boundary, i.e. activation memory) shrinks vs no-remat."""
    d = mx.sym.Variable("data")
    x = d
    for i in range(16):
        x = mx.sym.FullyConnected(x, num_hidden=512, name="fc%d" % i)
        # sigmoid's saved output is what segmentation drops
        x = mx.sym.Activation(x, act_type="sigmoid", name="s%d" % i)
    net = mx.sym.LinearRegressionOutput(
        x, mx.sym.Variable("label"), name="lro")
    shapes = dict(data=(256, 512), label=(256, 512))
    arg_shapes, _, _ = net.infer_shape(**shapes)
    rng = np.random.RandomState(2)
    args = {n: jnp.asarray(rng.uniform(-0.1, 0.1, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    key = jax.random.PRNGKey(0)

    def residual_bytes(remat):
        # public alias only exposes print_saved_residuals in this jax
        from jax._src.ad_checkpoint import saved_residuals

        fwd = lower_symbol(net, is_train=True, remat=remat)

        def loss(a):
            outs, _ = fwd(a, {}, key)
            return jnp.sum(outs[0])

        total = 0
        for aval, _ in saved_residuals(loss, args):
            if getattr(aval, "shape", ()):
                total += aval.size * aval.dtype.itemsize
        # parameters/inputs appear among residuals but are live either
        # way — subtract them to isolate the activation footprint
        return total - sum(int(np.prod(a.shape)) * 4
                           for a in args.values())

    base = residual_bytes(None)
    segmented = residual_bytes(8)
    mirrored = residual_bytes("mirror")
    # 16 fc+sigmoid pairs at no-remat save ~2 activations per pair; 8
    # segments keep only ~8 boundaries; mirror drops sigmoid outputs
    assert segmented < base / 2, (segmented, base)
    assert mirrored < base, (mirrored, base)


def test_resolve_remat_contract(monkeypatch):
    assert resolve_remat(None) is None
    assert resolve_remat("mirror") == "mirror"
    assert resolve_remat(4) == 4
    assert resolve_remat(0) is None
    # remat=True is a confusion with the boolean env var — refuse
    with pytest.raises(ValueError):
        resolve_remat(True)
    with pytest.raises(ValueError):
        resolve_remat(-2)
    with pytest.raises(ValueError):
        resolve_remat("layers")
    monkeypatch.setenv("TP_BACKWARD_DO_MIRROR", "1")
    assert resolve_remat(None) == "mirror"
    monkeypatch.delenv("TP_BACKWARD_DO_MIRROR")
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert resolve_remat(None) == "mirror"
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR")
    monkeypatch.setenv("TP_REMAT_SEGMENTS", "6")
    assert resolve_remat(None) == 6
    # explicit spec wins over env
    assert resolve_remat("mirror") == "mirror"


def test_fused_train_step_remat_param():
    """FusedTrainStep(remat=K) trains identically to remat=None."""
    from incubator_mxnet_tpu import parallel

    net = _conv_bn_net()
    losses = {}
    for remat in (None, 4):
        mx.random.seed(0)
        step = parallel.FusedTrainStep(
            net, {"data": (4, 3, 8, 8)}, {"softmax_label": (4,)},
            mesh=parallel.default_mesh(1), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), seed=0, remat=remat)
        rng = np.random.RandomState(3)
        batch = {"data": rng.randn(4, 3, 8, 8).astype(np.float32),
                 "softmax_label": rng.randint(0, 5, (4,))
                 .astype(np.float32)}
        for _ in range(3):
            step(batch)
        losses[remat] = {n: np.asarray(v) for n, v in
                         step.params.items()}
    for n in losses[None]:
        np.testing.assert_allclose(losses[None][n], losses[4][n],
                                   rtol=1e-5, atol=1e-7, err_msg=n)
