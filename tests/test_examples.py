"""Smoke-run every BASELINE-config example driver for a few steps
(reference CLI contract: ``example/image-classification/common/fit.py``,
``example/rnn``, ``example/ssd``)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(script, *args, timeout=900, n_devices=0):
    env = dict(os.environ)
    env["TP_EXAMPLES_FORCE_CPU"] = "1"
    if n_devices:
        env["TP_EXAMPLES_CPU_DEVICES"] = str(n_devices)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        cwd=EXAMPLES, env=env, capture_output=True, text=True,
        timeout=timeout)
    assert proc.returncode == 0, (
        "%s failed rc=%d\nstdout:\n%s\nstderr:\n%s"
        % (script, proc.returncode, proc.stdout[-2000:],
           proc.stderr[-2000:]))
    return proc.stderr + proc.stdout


def test_train_mnist_mlp():
    out = _run("train_mnist.py", "--network", "mlp", "--num-epochs", "1",
               "--num-examples", "256", "--batch-size", "64",
               "--kv-store", "local")
    assert "Train-accuracy" in out


def test_train_mnist_lenet():
    out = _run("train_mnist.py", "--network", "lenet", "--num-epochs", "1",
               "--num-examples", "128", "--batch-size", "32",
               "--kv-store", "local")
    assert "Train-accuracy" in out


def test_train_ptb_lstm():
    out = _run("train_ptb_lstm.py", "--num-epochs", "1",
               "--num-sentences", "48", "--vocab-size", "24",
               "--num-embed", "8", "--num-hidden", "8",
               "--num-lstm-layers", "1", "--batch-size", "8")
    assert "perplexity" in out.lower()


def test_train_cifar10_test_io():
    # --test-io exercises the CLI + data path without a training run
    out = _run("train_cifar10.py", "--test-io", "1", "--num-examples",
               "512", "--batch-size", "64", "--disp-batches", "2")
    assert "samples/sec" in out


@pytest.mark.slow
def test_train_cifar10():
    out = _run("train_cifar10.py", "--num-epochs", "1",
               "--num-examples", "128", "--batch-size", "32",
               "--kv-store", "local")
    assert "Train-accuracy" in out


@pytest.mark.slow
def test_train_imagenet_benchmark():
    # the reference's --benchmark 1 synthetic perf mode, shrunk
    out = _run("train_imagenet.py", "--benchmark", "1", "--network",
               "resnet", "--num-layers", "18", "--image-shape", "3,64,64",
               "--num-examples", "64", "--batch-size", "32",
               "--num-epochs", "1", "--kv-store", "local")
    assert "Train-accuracy" in out


@pytest.mark.slow
def test_train_ssd_small():
    pytest.importorskip("cv2")
    out = _run("train_ssd.py", "--small-config", "--data-shape", "64",
               "--num-epochs", "1", "--num-examples", "8",
               "--batch-size", "4")
    assert "multibox_loss" in out


def test_train_rcnn_small():
    out = _run("train_rcnn.py", "--num-epochs", "1", "--num-images", "2",
               "--image-size", "64", "--batch-rois", "8",
               "--post-nms", "8")
    assert "done" in out and "bbox-loss" in out


def test_train_transformer_lm():
    out = _run("train_transformer_lm.py", "--num-epochs", "2",
               "--seq-len", "16", "--num-batches", "4",
               "--vocab-size", "16")
    assert "Train-accuracy" in out and "done" in out


def test_train_transformer_lm_fused_head():
    """The flagship configuration: fused chunked softmax-xent head
    through FusedTrainStep, with segment remat."""
    out = _run("train_transformer_lm.py", "--num-epochs", "2",
               "--seq-len", "16", "--num-batches", "4",
               "--vocab-size", "16", "--fused-head", "--remat", "2")
    assert "Train-loss" in out and "done" in out


def test_train_transformer_lm_pipeline():
    """--pipeline L: the driver trains through SymbolPipelineTrainStep
    on an L-stage 'pp' mesh (round-4 verdict item #2's example-driver
    wiring)."""
    out = _run("train_transformer_lm.py", "--num-epochs", "2",
               "--seq-len", "16", "--num-batches", "4",
               "--vocab-size", "16", "--pipeline", "2",
               n_devices=2)
    assert "pipeline stages" in out and "Train-loss" in out \
        and "done" in out


def test_train_transformer_lm_moe():
    """--moe-experts E: the MoE model family trains through
    FusedTrainStep on a dp x ep mesh, logging balance/overflow stats
    (round-4 verdict item #3's example-driver wiring)."""
    out = _run("train_transformer_lm.py", "--num-epochs", "2",
               "--seq-len", "16", "--num-batches", "4",
               "--vocab-size", "16", "--moe-experts", "4",
               n_devices=4)
    assert "expert-parallel mesh" in out and "moe-aux=" in out \
        and "done" in out


@pytest.mark.slow
def test_serve_transformer_lm():
    """The serving driver: train the shift task, then generate through
    GenerationEngine under concurrent clients with mixed prompt lengths
    (compile bound + shift-chain continuation asserted inside)."""
    out = _run("serve_transformer_lm.py", "--num-epochs", "4",
               "--seq-len", "16", "--vocab-size", "16",
               "--embed", "16", "--heads", "2", "--clients", "3",
               "--requests-per-client", "2", "--new-tokens", "4",
               "--max-slots", "2")
    assert "served 6 requests" in out and "done" in out


def test_train_ctc_seq():
    """The warpctc family (reference example/warpctc): LSTM + CTCLoss
    learns unsegmented digit sequences to >0.7 exact-match (asserted
    inside the driver)."""
    out = _run("train_ctc_seq.py")  # defaults: converges to ~0.98
    assert "seq-accuracy" in out and "done" in out


def test_train_bayesian_sgld():
    """The Bayesian-methods family (reference example/bayesian-methods):
    SGLD posterior sampling; the posterior-mean prediction must hold up
    (asserted inside the driver)."""
    out = _run("train_bayesian_sgld.py", "--num-epochs", "24",
               "--burn-in", "12")
    assert "posterior-mean" in out and "done" in out


def test_train_fcn_seg():
    """The FCN family (reference example/fcn-xs): Deconvolution
    upsampling + per-pixel SoftmaxOutput(multi_output) learns the
    synthetic shape-segmentation task."""
    out = _run("train_fcn_seg.py", "--num-epochs", "5",
               "--num-batches", "6")
    assert "pixel-accuracy" in out and "done" in out
    import re

    accs = [float(m) for m in re.findall(r"pixel-accuracy=([0-9.]+)",
                                         out)]
    assert accs[-1] > 0.8, accs


def test_train_neural_style():
    """The neural-style family (reference example/neural-style):
    gradients flow to the INPUT image (attach_grad on a non-parameter)
    — the loss must descend by an order of magnitude."""
    out = _run("train_neural_style.py", "--steps", "25", "--size", "40")
    assert "style-loss" in out and "done" in out


def test_train_word2vec_nce():
    """The NCE example family (reference example/nce-loss): shared-
    weight Embedding + sampled negatives + LogisticRegressionOutput;
    the deterministic co-occurrence task must be learned outright."""
    out = _run("train_word2vec_nce.py", "--num-epochs", "8",
               "--vocab-size", "128", "--num-batches", "8")
    assert "nce-accuracy=1.0000" in out and "done" in out


def test_train_model_parallel_lstm():
    """The model-parallel-lstm family (reference
    example/model-parallel-lstm): each unrolled LSTM layer pinned to its
    own device via AttrScope(ctx_group)+group2ctx; the deterministic
    chain task must be learned (perplexity well under the vocab=16
    uniform level)."""
    out = _run("train_model_parallel_lstm.py", "--num-epochs", "2",
               "--num-batches", "20", n_devices=2)
    assert "'layer1': 'cpu(1)'" in out and "done" in out
    import re

    ppl = [float(m) for m in re.findall(r"Train-perplexity=([0-9.]+)",
                                        out)]
    assert ppl[-1] < 10.0, ppl


def test_train_rl_actor_critic():
    """The reinforcement-learning family (reference
    example/reinforcement-learning/parallel_actor_critic): batched
    multi-env rollouts + GAE + one A2C forward/backward per update on
    the built-in CartPole; the policy must clearly beat the ~20-step
    random baseline."""
    out = _run("train_rl_actor_critic.py", "--updates", "100",
               "--disp", "50")
    assert "done" in out
    import re

    final = re.search(r"final mean-episode-length=([0-9.]+)", out)
    assert final and float(final.group(1)) > 60.0, out[-500:]


def test_train_stochastic_depth():
    """The stochastic-depth family (reference example/stochastic-depth):
    residual blocks whose compute branch a per-batch Bernoulli gate
    skips during training, composed as BaseModule subclasses inside a
    SequentialModule; the expectation-path prediction must match what
    training reached."""
    out = _run("train_stochastic_depth.py")
    assert "done" in out
    import re

    acc = re.search(r"Predict-accuracy=([0-9.]+)", out)
    assert acc and float(acc.group(1)) > 0.9, out[-500:]


def test_train_dsd():
    """The DSD family (reference example/dsd): a user-registered
    pruning SGD (topk-mask of |w|) trains dense -> sparse -> dense; the
    sparse phase must actually hold the target sparsity (asserted in
    the driver) and every phase must stay accurate."""
    out = _run("train_dsd.py")
    assert "done" in out and "Sparsity Update" in out
    import re

    accs = [float(m) for m in
            re.findall(r"phase \w+: accuracy=([0-9.]+)", out)]
    assert len(accs) == 3 and min(accs) > 0.9, accs


def test_train_dec():
    """The DEC family (reference example/dec): autoencoder pretrain ->
    latent k-means -> KL refinement through a three-input CustomOp whose
    backward supplies the paper's closed-form z/mu gradients; the driver
    asserts cluster accuracy AND that the KL objective descends."""
    out = _run("train_dec.py")
    assert "done" in out and "kmeans cluster-accuracy" in out
    import re

    acc = re.search(r"final cluster-accuracy=([0-9.]+)", out)
    assert acc and float(acc.group(1)) > 0.9, out[-500:]


def test_train_adversary_fgsm():
    """The adversary family (reference example/adversary): FGSM input
    perturbation via Module's inputs_need_grad binding; clean accuracy
    must be high and adversarial accuracy collapsed (asserted in the
    driver)."""
    out = _run("train_adversary_fgsm.py")
    assert "done" in out and "fgsm-accuracy" in out


def test_train_captcha():
    """The captcha family (reference example/captcha): one conv trunk,
    four SoftmaxOutput heads trained jointly through a Group symbol and
    multi-label Module; exact-match accuracy (all digits right) is
    asserted in the driver."""
    out = _run("train_captcha.py")
    assert "done" in out
    import re

    acc = re.search(r"exact-match accuracy=([0-9.]+)", out)
    assert acc and float(acc.group(1)) > 0.8, out[-500:]


def test_train_speech_frame():
    """The speech family (reference example/speech-demo, minus Kaldi):
    continuous filterbank frames through a stacked BiLSTM with a
    time-distributed softmax; framewise accuracy asserted in the
    driver."""
    out = _run("train_speech_frame.py")
    assert "done" in out and "frame-accuracy" in out


def test_train_dcgan():
    out = _run("train_dcgan.py", "--num-epochs", "1",
               "--num-batches", "2", "--size", "32")
    assert "done" in out and "D(G(z))" in out


def test_train_matrix_fact():
    out = _run("train_matrix_fact.py", "--num-epochs", "6",
               "--num-ratings", "1024")
    assert "final-rmse=" in out
    rmse = float(out.split("final-rmse=")[1].split()[0])
    assert rmse < 0.5, rmse  # planted low-rank model is learnable


def test_train_autoencoder():
    out = _run("train_autoencoder.py", "--num-epochs", "3",
               "--num-examples", "256")
    assert "final-mse=" in out
    mse = float(out.rsplit("final-mse=", 1)[1].split()[0])
    assert mse < 0.15, mse  # epoch 0 starts >0.2; learning must show


def test_train_multi_task():
    out = _run("train_multi_task.py", "--num-epochs", "5",
               "--num-examples", "512")
    assert "parity-acc=" in out
    acc = float(out.rsplit("parity-acc=", 1)[1].split()[0])
    assert acc > 0.9, acc


def test_train_text_cnn():
    out = _run("train_text_cnn.py", "--num-epochs", "5",
               "--num-examples", "512")
    assert "final-acc=" in out
    acc = float(out.split("final-acc=")[1].split()[0])
    assert acc > 0.85, acc


def test_train_bi_lstm_sort():
    out = _run("train_bi_lstm_sort.py", "--num-epochs", "4",
               "--num-examples", "512")
    assert "final-acc=" in out
    acc = float(out.rsplit("final-acc=", 1)[1].split()[0])
    assert acc > 0.5, acc  # chance is 1/16; bidirectional context needed


def test_train_custom_op():
    """The numpy-ops family (reference example/numpy-ops): a python
    CustomOp loss layer trains a real Module loop (>0.9 accuracy
    asserted inside the driver)."""
    out = _run("train_custom_op.py")
    assert "Train-accuracy" in out and "done" in out


def test_train_autograd_function():
    """autograd.Function in an imperative loop: straight-through sign
    activation trains past chance (>0.7 asserted inside the driver)."""
    out = _run("train_autograd_function.py", "--num-epochs", "8")
    assert "Train-accuracy" in out and "done" in out


def test_train_svm_mnist():
    """The svm_mnist family (reference example/svm_mnist): SVMOutput
    hinge heads — both L2 (squared hinge) and L1 (use_linear) — train
    to >0.9 accuracy (asserted inside the driver)."""
    out = _run("train_svm_mnist.py")
    assert "Train-accuracy" in out and "done" in out
    out = _run("train_svm_mnist.py", "--use-linear")
    assert "done" in out
