"""SymbolPipelineTrainStep: pipeline-parallel training of ARBITRARY
Symbols (round-4 verdict item #2 — the generalization of the
transformer-only ``PipelineTrainStep``).

Reference anchor: the group2ctx placement machinery this generalizes,
``src/executor/graph_executor.cc:279-393``.

The parity oracle everywhere is ``FusedTrainStep(grad_accum=M)`` on a
single device: identical microbatch slicing, gradient summation, aux
threading order, and optimizer ops — so pipelined training must match
it to float precision (sgd; adam's sqrt-normalized update amplifies
float roundoff near zero states, so adam tolerances are looser).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.parallel import SymbolPipelineTrainStep


def _mlp(layers=8, hidden=16, classes=5):
    x = mx.sym.Variable("data")
    for i in range(layers):
        x = mx.sym.FullyConnected(x, num_hidden=hidden, name="fc%d" % i)
        x = mx.sym.Activation(x, act_type="relu", name="r%d" % i)
    x = mx.sym.FullyConnected(x, num_hidden=classes, name="out")
    return mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _convbn(layers=4):
    x = mx.sym.Variable("data")
    for i in range(layers):
        x = mx.sym.Convolution(x, num_filter=8, kernel=(3, 3),
                               pad=(1, 1), name="c%d" % i)
        x = mx.sym.BatchNorm(x, name="bn%d" % i)
        x = mx.sym.Activation(x, act_type="relu", name="cr%d" % i)
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg",
                       kernel=(1, 1), name="gp")
    x = mx.sym.Flatten(x, name="fl")
    x = mx.sym.FullyConnected(x, num_hidden=5, name="out")
    return mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _batch(rng, shapes, classes=5):
    return {"data": rng.randn(*shapes["data"]).astype(np.float32),
            "softmax_label": rng.randint(
                0, classes, shapes["softmax_label"]).astype(np.float32)}


def test_mlp_pp4_matches_single_device_exactly():
    """8-layer MLP auto-partitioned over pp=4: parameter trajectory
    matches FusedTrainStep(grad_accum=4) to float precision."""
    net = _mlp()
    shapes = {"data": (8, 12), "softmax_label": (8,)}
    mesh = parallel.build_mesh({"pp": 4})
    fused = parallel.FusedTrainStep(
        net, {"data": shapes["data"]}, {"softmax_label": (8,)},
        mesh=parallel.default_mesh(1), optimizer="sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
        initializer=mx.initializer.Xavier(), seed=0, grad_accum=4)
    pp = SymbolPipelineTrainStep(
        net, {"data": shapes["data"]}, {"softmax_label": (8,)},
        mesh=mesh, num_microbatches=4, optimizer="sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
        initializer=mx.initializer.Xavier(), seed=0)
    assert len(pp.stage_assignment) == 4
    assert all(len(s) >= 2 for s in pp.stage_assignment)
    pp.set_params({n: np.asarray(v) for n, v in fused.params.items()})
    rng = np.random.RandomState(0)
    batch = _batch(rng, shapes)
    for _ in range(4):
        fused(batch)
        pp(batch)
    got = pp.get_params()
    for n, v in fused.params.items():
        np.testing.assert_allclose(np.asarray(v), got[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_convbn_pp2_aux_threading_matches_grad_accum():
    """conv+BatchNorm net over pp=2: BN moving stats (aux) thread per
    REAL tick in microbatch order — exactly grad_accum's sequential
    scan; bubble ticks must not pollute them."""
    net = _convbn()
    data_s = {"data": (8, 3, 8, 8)}
    lab_s = {"softmax_label": (8,)}
    mesh = parallel.build_mesh({"pp": 2})
    fused = parallel.FusedTrainStep(
        net, data_s, lab_s, mesh=parallel.default_mesh(1),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        initializer=mx.initializer.Xavier(), seed=0, grad_accum=4)
    pp = SymbolPipelineTrainStep(
        net, data_s, lab_s, mesh=mesh, num_microbatches=4,
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        initializer=mx.initializer.Xavier(), seed=0)
    pp.set_params({n: np.asarray(v) for n, v in fused.params.items()},
                  {n: np.asarray(v) for n, v in fused.aux.items()})
    rng = np.random.RandomState(1)
    batch = {"data": rng.randn(8, 3, 8, 8).astype(np.float32),
             "softmax_label": rng.randint(0, 5, (8,))
             .astype(np.float32)}
    for _ in range(3):
        fused(batch)
        pp(batch)
    got = pp.get_params()
    for n, v in fused.params.items():
        np.testing.assert_allclose(np.asarray(v), got[n].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=n)
    for n, v in fused.aux.items():  # the moving BN stats themselves
        np.testing.assert_allclose(np.asarray(v), got[n].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_dp_pp_composition_matches_pp_only():
    """dp2 x pp2: data parallelism on the other mesh axis shards each
    microbatch; gradients psum over dp, so the parameter trajectory
    equals the pp-only run on the same global batch."""
    net = _mlp(layers=4)
    data_s = {"data": (8, 12)}
    lab_s = {"softmax_label": (8,)}
    common = dict(num_microbatches=2, optimizer="sgd",
                  optimizer_params={"learning_rate": 0.5},
                  initializer=mx.initializer.Xavier(), seed=0)
    pp = SymbolPipelineTrainStep(
        net, data_s, lab_s, mesh=parallel.build_mesh({"pp": 2}),
        **common)
    dpp = SymbolPipelineTrainStep(
        net, data_s, lab_s,
        mesh=parallel.build_mesh({"dp": 2, "pp": 2}), **common)
    dpp.set_params({n: v.asnumpy() for n, v in pp.get_params().items()})
    rng = np.random.RandomState(2)
    batch = _batch(rng, {"data": (8, 12), "softmax_label": (8,)})
    for _ in range(3):
        l1 = pp(batch)
        l2 = dpp(batch)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    p1, p2 = pp.get_params(), dpp.get_params()
    for n in p1:
        np.testing.assert_allclose(p1[n].asnumpy(), p2[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_transformer_symbol_pipelines_and_learns():
    """The REAL transformer-LM symbol (fused head) auto-partitions over
    pp=4 and learns the shift task — the round-4 sealed-demo
    ``PipelineTrainStep`` capability, now from the generic Symbol path."""
    B, S, E, H, L, V = 8, 16, 32, 2, 4, 64
    M = 4
    # transformer_lm bakes batch_size into its reshapes: build the
    # symbol at the PER-DEVICE microbatch size the stage bodies see
    net = mx.models.transformer_lm(
        vocab_size=V, embed=E, heads=H, num_layers=L, seq_len=S,
        batch_size=B // M, dtype="float32", head="fused")
    pp = SymbolPipelineTrainStep(
        net, {"data": (B, S)}, {"softmax_label": (B, S)},
        mesh=parallel.build_mesh({"pp": 4}), num_microbatches=M,
        optimizer="adam", optimizer_params={"learning_rate": 1e-2},
        initializer=mx.initializer.Xavier(), seed=0)
    rng = np.random.RandomState(0)
    data = rng.randint(0, V, (B, S)).astype(np.float32)
    labels = np.roll(data, -1, axis=1)
    first = last = None
    for _ in range(30):
        last = pp({"data": data, "softmax_label": labels}) / (B * S)
        if first is None:
            first = last
    assert last < first * 0.2, (first, last)


def test_guards():
    """Clear errors: too many stages for the cut structure, loss head
    not in the final stage, indivisible batch, non-batch-major input."""
    # a 2-layer net cannot split into 8 single-tensor stages
    net = _mlp(layers=1)
    with pytest.raises(MXNetError, match="cut points"):
        SymbolPipelineTrainStep(
            net, {"data": (8, 12)}, {"softmax_label": (8,)},
            mesh=parallel.build_mesh({"pp": 8}), num_microbatches=4)
    net = _mlp()
    with pytest.raises(MXNetError, match="divide"):
        SymbolPipelineTrainStep(
            net, {"data": (6, 12)}, {"softmax_label": (6,)},
            mesh=parallel.build_mesh({"pp": 4}), num_microbatches=4)
    with pytest.raises(MXNetError, match="batch-major|leading"):
        SymbolPipelineTrainStep(
            net, {"data": (8, 12)}, {"softmax_label": (4,)},
            mesh=parallel.build_mesh({"pp": 4}), num_microbatches=4)


def test_pipeline_checkpoint_resume_bit_exact(tmp_path):
    """save_sharded/restore_sharded round-trip the pipelined trainer's
    stage-stacked state: a restored step continues EXACTLY like the
    uninterrupted run (params, optimizer states, update counter)."""
    from incubator_mxnet_tpu.parallel.checkpoint import (restore_sharded,
                                                         save_sharded)

    net = _mlp(layers=4)
    shapes = ({"data": (8, 12)}, {"softmax_label": (8,)})
    mesh = parallel.build_mesh({"pp": 2})
    kw = dict(mesh=mesh, num_microbatches=2, optimizer="adam",
              optimizer_params={"learning_rate": 0.05},
              initializer=mx.initializer.Xavier())
    mx.random.seed(5)
    pp = SymbolPipelineTrainStep(net, *shapes, **kw)
    rng = np.random.RandomState(3)
    batch = _batch(rng, {"data": (8, 12), "softmax_label": (8,)})
    for _ in range(2):
        pp(batch)
    ck = str(tmp_path / "ppck")
    save_sharded(ck, pp)
    pp(batch)  # the uninterrupted continuation

    mx.random.seed(99)  # deliberately different init
    pp2 = SymbolPipelineTrainStep(net, *shapes, **kw)
    restore_sharded(ck, pp2)
    assert pp2.num_update == 2
    pp2(batch)
    np.testing.assert_allclose(np.asarray(pp.flat_params),
                               np.asarray(pp2.flat_params),
                               rtol=1e-6, atol=1e-7)


def test_resnet_pipelines_exactly():
    """The REAL models.resnet family auto-partitions over pp=4 (skip
    connections and BN aux intact) and matches the
    FusedTrainStep(grad_accum=4) oracle to float precision — the
    'ResNet family' case the round-4 verdict named."""
    net = mx.models.resnet(num_layers=20, num_classes=10,
                           image_shape=(3, 16, 16))
    data_s = {"data": (8, 3, 16, 16)}
    lab_s = {"softmax_label": (8,)}
    fused = parallel.FusedTrainStep(
        net, data_s, lab_s, mesh=parallel.default_mesh(1),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        initializer=mx.initializer.Xavier(), seed=0, grad_accum=4)
    pp = SymbolPipelineTrainStep(
        net, data_s, lab_s, mesh=parallel.build_mesh({"pp": 4}),
        num_microbatches=4, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1},
        initializer=mx.initializer.Xavier(), seed=0)
    assert len(pp.stage_assignment) == 4
    pp.set_params({n: np.asarray(v) for n, v in fused.params.items()},
                  {n: np.asarray(v) for n, v in fused.aux.items()})
    rng = np.random.RandomState(0)
    batch = {"data": rng.randn(8, 3, 16, 16).astype(np.float32),
             "softmax_label": rng.randint(0, 10, (8,))
             .astype(np.float32)}
    for _ in range(2):
        fused(batch)
        pp(batch)
    got = pp.get_params()
    for n, v in fused.params.items():
        np.testing.assert_allclose(np.asarray(v), got[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_input_reentry_in_later_stage_uses_correct_microbatch():
    """A net whose INPUT is consumed again past the first cut: the
    later stage must read the microbatch its in-flight activation came
    from (slot = t - s), not tick t's — float-exact vs the oracle."""
    d = mx.sym.Variable("data")
    x = d
    for i in range(4):
        x = mx.sym.FullyConnected(x, num_hidden=16, name="fc%d" % i)
        x = mx.sym.Activation(x, act_type="relu", name="r%d" % i)
    x = mx.sym.Concat(x, d, dim=1, name="skip_in")  # data re-enters
    x = mx.sym.FullyConnected(x, num_hidden=5, name="out")
    net = mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                               name="softmax")
    fused = parallel.FusedTrainStep(
        net, {"data": (8, 12)}, {"softmax_label": (8,)},
        mesh=parallel.default_mesh(1), optimizer="sgd",
        optimizer_params={"learning_rate": 0.5},
        initializer=mx.initializer.Xavier(), seed=0, grad_accum=4)
    pp = SymbolPipelineTrainStep(
        net, {"data": (8, 12)}, {"softmax_label": (8,)},
        mesh=parallel.build_mesh({"pp": 2}), num_microbatches=4,
        optimizer="sgd", optimizer_params={"learning_rate": 0.5},
        initializer=mx.initializer.Xavier(), seed=0)
    assert any("skip_in" in s for s in pp.stage_assignment[1:])
    pp.set_params({n: np.asarray(v) for n, v in fused.params.items()})
    rng = np.random.RandomState(0)
    batch = _batch(rng, {"data": (8, 12), "softmax_label": (8,)})
    for _ in range(3):
        fused(batch)
        pp(batch)
    got = pp.get_params()
    for n, v in fused.params.items():
        np.testing.assert_allclose(np.asarray(v), got[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)
