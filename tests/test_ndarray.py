"""NDArray tests — ported semantics of reference
``tests/python/unittest/test_ndarray.py`` (numpy-oracle philosophy,
SURVEY.md §4)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    np.testing.assert_allclose(a.asnumpy(), np.zeros((2, 3)))

    b = mx.nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    assert b.sum().asscalar() == 4

    c = mx.nd.full((2, 2), 7.0)
    np.testing.assert_allclose(c.asnumpy(), np.full((2, 2), 7.0))

    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.float32  # float64 downcast like reference default

    e = mx.nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), np.arange(0, 10, 2))


def test_elementwise_arith():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    a, b = mx.nd.array(x), mx.nd.array(y)

    np.testing.assert_allclose((a + b).asnumpy(), x + y, rtol=1e-6)
    np.testing.assert_allclose((a - b).asnumpy(), x - y, rtol=1e-6)
    np.testing.assert_allclose((a * b).asnumpy(), x * y, rtol=1e-6)
    np.testing.assert_allclose((a / b).asnumpy(), x / y, rtol=1e-5)
    np.testing.assert_allclose((a + 1).asnumpy(), x + 1, rtol=1e-6)
    np.testing.assert_allclose((2 - a).asnumpy(), 2 - x, rtol=1e-6)
    np.testing.assert_allclose((a * 3).asnumpy(), x * 3, rtol=1e-6)
    np.testing.assert_allclose((1 / (a + 10)).asnumpy(), 1 / (x + 10),
                               rtol=1e-5)
    np.testing.assert_allclose((-a).asnumpy(), -x)
    np.testing.assert_allclose(abs(a).asnumpy(), np.abs(x))
    np.testing.assert_allclose((a ** 2).asnumpy(), x ** 2, rtol=1e-5)


def test_inplace_ops():
    x = np.ones((2, 3), dtype=np.float32)
    a = mx.nd.array(x)
    a += 2
    np.testing.assert_allclose(a.asnumpy(), x + 2)
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), (x + 2) * 3)


def test_slicing_views_share_storage():
    # NDArray::Slice/At share storage (include/mxnet/ndarray.h:156-172)
    a = mx.nd.zeros((4, 3))
    b = a[1:3]
    b[:] = 5.0
    expect = np.zeros((4, 3), dtype=np.float32)
    expect[1:3] = 5.0
    np.testing.assert_allclose(a.asnumpy(), expect)

    row = a[0]
    row[:] = 2.0
    expect[0] = 2.0
    np.testing.assert_allclose(a.asnumpy(), expect)


def test_reshape_view_shares_storage():
    a = mx.nd.zeros((2, 6))
    b = a.reshape((3, 4))
    b[:] = 1.0
    np.testing.assert_allclose(a.asnumpy(), np.ones((2, 6)))
    c = a.reshape((4, -1))
    assert c.shape == (4, 3)


def test_setitem():
    a = mx.nd.zeros((3, 3))
    a[1] = 1.0
    a[2] = np.array([1, 2, 3])
    out = a.asnumpy()
    np.testing.assert_allclose(out[1], np.ones(3))
    np.testing.assert_allclose(out[2], [1, 2, 3])


def test_unary_ops_vs_numpy():
    rng = np.random.RandomState(1)
    x = (rng.rand(5, 4).astype(np.float32) + 0.1)
    a = mx.nd.array(x)
    for name, ref in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                      ("square", np.square), ("tanh", np.tanh),
                      ("sign", np.sign), ("floor", np.floor),
                      ("ceil", np.ceil)]:
        got = getattr(mx.nd, name)(a).asnumpy()
        np.testing.assert_allclose(got, ref(x), rtol=1e-5, atol=1e-6,
                                   err_msg=name)
    np.testing.assert_allclose(mx.nd.sigmoid(a).asnumpy(),
                               1 / (1 + np.exp(-x)), rtol=1e-5)
    np.testing.assert_allclose(mx.nd.relu(mx.nd.array(x - 0.5)).asnumpy(),
                               np.maximum(x - 0.5, 0), rtol=1e-6)


def test_reductions():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 4).astype(np.float32)
    a = mx.nd.array(x)
    np.testing.assert_allclose(mx.nd.sum(a).asnumpy(), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(mx.nd.sum(a, axis=1).asnumpy(), x.sum(axis=1),
                               rtol=1e-5)
    np.testing.assert_allclose(
        mx.nd.sum(a, axis=(0, 2), keepdims=True).asnumpy(),
        x.sum(axis=(0, 2), keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(mx.nd.mean(a, axis=0).asnumpy(), x.mean(0),
                               rtol=1e-5)
    np.testing.assert_allclose(mx.nd.max(a, axis=2).asnumpy(), x.max(2))
    np.testing.assert_allclose(
        mx.nd.argmax(a, axis=1).asnumpy(), x.argmax(1).astype(np.float32))


def test_broadcast_ops():
    x = np.random.rand(2, 1, 4).astype(np.float32)
    y = np.random.rand(1, 3, 4).astype(np.float32)
    a, b = mx.nd.array(x), mx.nd.array(y)
    np.testing.assert_allclose(mx.nd.broadcast_add(a, b).asnumpy(), x + y,
                               rtol=1e-6)
    np.testing.assert_allclose(mx.nd.broadcast_mul(a, b).asnumpy(), x * y,
                               rtol=1e-6)
    c = mx.nd.broadcast_to(mx.nd.array(np.ones((1, 4))), shape=(3, 4))
    assert c.shape == (3, 4)


def test_dot():
    x = np.random.rand(3, 4).astype(np.float32)
    y = np.random.rand(4, 5).astype(np.float32)
    out = mx.nd.dot(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    np.testing.assert_allclose(out, x.dot(y), rtol=1e-5)
    out_t = mx.nd.dot(mx.nd.array(x), mx.nd.array(y.T),
                      transpose_b=True).asnumpy()
    np.testing.assert_allclose(out_t, x.dot(y), rtol=1e-5)


def test_shape_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    a = mx.nd.array(x)
    np.testing.assert_allclose(mx.nd.transpose(a).asnumpy(), x.T)
    np.testing.assert_allclose(
        mx.nd.transpose(a, axes=(1, 0, 2)).asnumpy(), x.transpose(1, 0, 2))
    np.testing.assert_allclose(mx.nd.Flatten(a).asnumpy(), x.reshape(2, -1))
    np.testing.assert_allclose(
        mx.nd.Reshape(a, shape=(4, 6)).asnumpy(), x.reshape(4, 6))
    np.testing.assert_allclose(
        mx.nd.expand_dims(a, axis=1).asnumpy(), x[:, None])
    np.testing.assert_allclose(
        mx.nd.slice_axis(a, axis=1, begin=1, end=3).asnumpy(), x[:, 1:3])
    np.testing.assert_allclose(mx.nd.tile(a, reps=(1, 2, 1)).asnumpy(),
                               np.tile(x, (1, 2, 1)))
    np.testing.assert_allclose(mx.nd.repeat(a, repeats=2, axis=0).asnumpy(),
                               np.repeat(x, 2, 0))


def test_concat_split():
    x = np.random.rand(2, 3).astype(np.float32)
    y = np.random.rand(2, 3).astype(np.float32)
    out = mx.nd.Concat(mx.nd.array(x), mx.nd.array(y), dim=1)
    np.testing.assert_allclose(out.asnumpy(), np.concatenate([x, y], 1))
    parts = mx.nd.SliceChannel(mx.nd.array(x), num_outputs=3, axis=1)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[1].asnumpy(), x[:, 1:2])


def test_copyto_and_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu(0))
    b = mx.nd.zeros((2, 2), ctx=mx.cpu(1))
    a.copyto(b)
    np.testing.assert_allclose(b.asnumpy(), np.ones((2, 2)))
    c = a.as_in_context(mx.cpu(2))
    assert c.context == mx.cpu(2) or c.context.device_type == "cpu"


def test_astype_cast():
    a = mx.nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = mx.nd.Cast(a, dtype="float16")
    assert c.dtype == np.float16


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.params")
    d = {"w": mx.nd.array(np.random.rand(3, 3).astype(np.float32)),
         "b": mx.nd.ones((7,))}
    mx.nd.save(fname, d)
    back = mx.nd.load(fname)
    assert set(back) == {"w", "b"}
    np.testing.assert_allclose(back["w"].asnumpy(), d["w"].asnumpy())

    lst = [mx.nd.ones((2,)), mx.nd.zeros((3,))]
    mx.nd.save(fname, lst)
    back = mx.nd.load(fname)
    assert isinstance(back, list) and len(back) == 2


def test_random_reproducibility():
    mx.random.seed(42)
    a = mx.nd.random_uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random_uniform(shape=(5,)).asnumpy()
    np.testing.assert_allclose(a, b)
    c = mx.nd.random_normal(loc=1.0, scale=0.0, shape=(4,))
    np.testing.assert_allclose(c.asnumpy(), np.ones(4), atol=1e-6)


def test_indexing_ops():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5], dtype=np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10,
                          output_dim=4)
    np.testing.assert_allclose(out.asnumpy(), w[[1, 3, 5]])
    oh = mx.nd.one_hot(mx.nd.array(idx), depth=10)
    assert oh.shape == (3, 10)
    np.testing.assert_allclose(oh.asnumpy().argmax(1), [1, 3, 5])


def test_ordering_ops():
    x = np.random.rand(4, 6).astype(np.float32)
    a = mx.nd.array(x)
    np.testing.assert_allclose(mx.nd.sort(a, axis=1).asnumpy(),
                               np.sort(x, 1), rtol=1e-6)
    topk = mx.nd.topk(a, axis=1, k=2, ret_typ="value").asnumpy()
    np.testing.assert_allclose(topk, -np.sort(-x, 1)[:, :2], rtol=1e-6)


def test_wait_and_engine():
    a = mx.nd.ones((100, 100))
    b = mx.nd.dot(a, a)
    b.wait_to_read()
    mx.nd.waitall()
    assert b.shape == (100, 100)


def test_save_load_scalar_no_desync(tmp_path):
    """A 0-d NDArray persists as shape (1,): writing ndim=0 followed by
    Context/type/payload would desync the stream on load (the ndim==0
    branch early-returns per the reference's empty-NDArray semantics,
    ``ndarray.cc:693``) and corrupt every subsequent array."""
    fname = str(tmp_path / "scalar.params")
    d = {"s": mx.nd.array(np.asarray(3.5, np.float32)),
         "w": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))}
    assert d["s"].shape == ()
    mx.nd.save(fname, d)
    back = mx.nd.load(fname)
    assert back["s"].shape == (1,)
    assert float(back["s"].asnumpy()[0]) == 3.5
    np.testing.assert_allclose(back["w"].asnumpy(), d["w"].asnumpy())
