"""Module tests — reference ``tests/python/unittest/test_module.py`` +
``tests/python/train/test_mlp.py`` convergence philosophy (SURVEY.md §4)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _make_dataset(n=400, nclass=4, dim=16, seed=3):
    rng = np.random.RandomState(seed)
    centers = rng.randn(nclass, dim).astype(np.float32) * 3
    y = rng.randint(0, nclass, n)
    x = centers[y] + rng.randn(n, dim).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def _mlp(nclass=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=nclass)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_mlp_converges():
    np.random.seed(0)
    mx.random.seed(0)
    x, y = _make_dataset()
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            eval_metric="acc",
            initializer=mx.initializer.Xavier())
    score = mod.score(train, "acc")
    assert score[0][1] > 0.95, "MLP did not converge: %s" % score


def test_module_predict_and_score():
    x, y = _make_dataset(n=100)
    it = mx.io.NDArrayIter(x, y, batch_size=25)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (100, 4)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(100),
                               rtol=1e-5)


def test_module_multi_device_data_parallel():
    # 2 CPU contexts stand in for 2 chips (reference multi_lenet pattern)
    x, y = _make_dataset(n=200)
    train = mx.io.NDArrayIter(x, y, batch_size=40)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            kvstore="local",
            initializer=mx.initializer.Xavier())
    score = mod.score(train, "acc")
    assert score[0][1] > 0.9, score


def test_multi_device_matches_single_device():
    # numerical equivalence single- vs multi-device (nightly multi_lenet.py)
    x, y = _make_dataset(n=80, seed=11)
    np.random.seed(0)
    mx.random.seed(0)

    def run(ctxs):
        it = mx.io.NDArrayIter(x, y, batch_size=40)
        mod = mx.mod.Module(_mlp(), context=ctxs)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        np.random.seed(42)
        mod.init_params(initializer=mx.initializer.Xavier())
        mod.init_optimizer(kvstore="local", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for _ in range(3):
            it.reset()
            for batch in it:
                mod.forward_backward(batch)
                mod.update()
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    p1 = run([mx.cpu(0)])
    p2 = run([mx.cpu(0), mx.cpu(1)])
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=1e-5,
                                   err_msg=k)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _make_dataset(n=100)
    it = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)

    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    out1 = mod.predict(it).asnumpy()
    out2 = mod2.predict(it).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-5)


def test_optimizers_each_reduce_loss():
    x, y = _make_dataset(n=200, seed=5)
    for opt, params in [
        ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
        ("adam", {"learning_rate": 0.01}),
        ("rmsprop", {"learning_rate": 0.01}),
        ("adagrad", {"learning_rate": 0.1}),
        ("adadelta", {}),
        ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
        ("ftrl", {"learning_rate": 0.5}),
        ("adamax", {"learning_rate": 0.01}),
        ("nadam", {"learning_rate": 0.01}),
    ]:
        train = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(train, num_epoch=3, optimizer=opt,
                optimizer_params=params,
                initializer=mx.initializer.Xavier())
        score = mod.score(train, "acc")[0][1]
        assert score > 0.5, "%s failed to learn (acc=%.3f)" % (opt, score)


def test_metrics():
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m = mx.metric.Accuracy()
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6

    # framewise labels: (B, T, C) class scores argmax over the class
    # axis against (B, T) labels — the reference argmaxes only when
    # the prediction carries an EXTRA axis (metric.py:391 ndim rule)
    frame_pred = mx.nd.array([[[0.9, 0.1], [0.2, 0.8]],
                              [[0.6, 0.4], [0.3, 0.7]]])  # (2, 2, C=2)
    frame_label = mx.nd.array([[0, 1], [1, 1]])       # (B=2, T=2)
    fm = mx.metric.Accuracy(axis=-1)
    fm.update([frame_label], [frame_pred])
    assert abs(fm.get()[1] - 3.0 / 4) < 1e-6

    # equal-rank shape mismatches are no longer silently argmaxed into
    # nonsense counts — they raise
    with pytest.raises(mx.base.MXNetError):
        mx.metric.Accuracy().update(
            [frame_label], [mx.nd.array([[0.9, 0.1], [0.2, 0.8],
                                         [0.6, 0.4], [0.3, 0.7]])])

    ce = mx.metric.create("ce")
    ce.update([label], [pred])
    expect = -(np.log(0.9) + np.log(0.8) + np.log(0.3)) / 3
    assert abs(ce.get()[1] - expect) < 1e-5

    comp = mx.metric.create(["acc", "ce"])
    comp.update([label], [pred])
    names, vals = comp.get()
    assert len(names) == 2

    mse = mx.metric.MSE()
    mse.update([mx.nd.array([1.0, 2.0])],
               [mx.nd.array([[1.5], [2.5]])])
    assert abs(mse.get()[1] - 0.25) < 1e-6


def test_lr_scheduler():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(5) == 1.0
    assert s(25) == 0.25

    ms = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    ms.base_lr = 1.0
    assert ms(3) == 1.0
    assert abs(ms(10) - 0.1) < 1e-9


def test_ndarray_iter_pad_and_shuffle():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(x, np.zeros(10, np.float32), batch_size=4,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it2 = mx.io.NDArrayIter(x, None, batch_size=5,
                            last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_initializers():
    w = mx.nd.zeros((64, 32))
    mx.initializer.Xavier()(mx.initializer.InitDesc("fc_weight"), w)
    arr = w.asnumpy()
    assert arr.std() > 0
    bound = np.sqrt(3.0 / ((64 + 32) / 2))
    assert abs(arr).max() <= bound + 1e-6

    b = mx.nd.ones((5,))
    mx.initializer.Uniform()(mx.initializer.InitDesc("fc_bias"), b)
    np.testing.assert_allclose(b.asnumpy(), np.zeros(5))  # bias → 0

    g = mx.nd.zeros((5,))
    mx.initializer.Uniform()(mx.initializer.InitDesc("bn_gamma"), g)
    np.testing.assert_allclose(g.asnumpy(), np.ones(5))
