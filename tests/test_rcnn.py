"""Faster R-CNN model family — reference
``example/rcnn/rcnn/symbol/symbol_vgg.py`` parity at the symbol level:
shape inference, test-net forward, proposal_target sampling, and a
train-net forward/backward step."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models import rcnn


def test_symbol_shapes():
    net = rcnn.get_symbol_test(num_classes=4, post_nms=50)
    _, outs, _ = net.infer_shape(data=(1, 3, 64, 64), im_info=(1, 3))
    assert outs == [(50, 5), (1, 50, 4), (1, 50, 16)]

    rpn = rcnn.get_symbol_rpn()
    args = rpn.list_arguments()
    assert "rpn_cls_score_weight" in args and "data" in args


def test_proposal_target_sampling():
    """The host sampler produces fixed-size ROI batches with
    class-specific targets (reference sample_rois semantics)."""
    np.random.seed(0)
    prop = rcnn.ProposalTargetProp(num_classes="3", batch_rois="8",
                                   fg_fraction="0.5")
    op = prop.create_operator(None, None, None)
    rois = np.array([[0, 0, 0, 10, 10],
                     [0, 20, 20, 40, 40],
                     [0, 1, 1, 11, 11],
                     [0, 50, 50, 60, 60]], np.float32)
    gt = np.array([[0, 0, 10, 10, 1],      # class 1
                   [20, 20, 40, 40, 2],    # class 2
                   [-1, -1, -1, -1, -1]],  # pad row: ignored
                  np.float32)
    out = [np.zeros((8, 5), np.float32), np.zeros(8, np.float32),
           np.zeros((8, 12), np.float32), np.zeros((8, 12), np.float32)]
    op.forward(True, ["write"] * 4, [rois, gt], out, [])
    out_rois, labels, targets, weights = out
    assert out_rois.shape == (8, 5)
    # foregrounds carry their gt class; gt boxes were appended so exact
    # matches exist
    assert set(labels) <= {0.0, 1.0, 2.0}
    assert (labels > 0).sum() >= 2
    for i in range(8):
        c = int(labels[i])
        if c > 0:
            assert weights[i, 4 * c:4 * c + 4].all()
            assert not weights[i, :4].any()
        else:
            assert not weights[i].any()
    # an exact-match roi has ~zero regression target
    exact = np.where(labels == 1)[0]
    if len(exact):
        i = exact[0]
        if np.allclose(out_rois[i, 1:], [0, 0, 10, 10]):
            assert np.abs(targets[i, 4:8]).max() < 1e-5


@pytest.mark.slow
def test_rcnn_test_net_forward():
    net = rcnn.get_symbol_test(num_classes=3, post_nms=20, pre_nms=200)
    ex = net.simple_bind(grad_req="null", data=(1, 3, 64, 64),
                         im_info=(1, 3))
    rng = np.random.RandomState(0)
    for n in ex.arg_dict:
        if n not in ("data", "im_info"):
            ex.arg_dict[n][:] = mx.nd.array(
                rng.uniform(-0.01, 0.01,
                            ex.arg_dict[n].shape).astype(np.float32))
    ex.arg_dict["data"][:] = mx.nd.array(
        rng.rand(1, 3, 64, 64).astype(np.float32))
    ex.arg_dict["im_info"][:] = mx.nd.array(
        np.array([[64, 64, 1.0]], np.float32))
    rois, cls_prob, bbox = [o.asnumpy() for o in
                            ex.forward(is_train=False)]
    assert rois.shape == (20, 5)
    assert cls_prob.shape == (1, 20, 3)
    np.testing.assert_allclose(cls_prob.sum(-1), 1.0, rtol=1e-4)
    assert np.isfinite(bbox).all()


@pytest.mark.slow
def test_rcnn_train_net_step():
    """End-to-end fwd+bwd through RPN losses + proposal_target (host
    CustomOp) + Fast R-CNN losses."""
    np.random.seed(1)
    net = rcnn.get_symbol_train(num_classes=3, batch_rois=8,
                                post_nms=16, pre_nms=100)
    h = w = 64
    fh = fw = h // 16
    na = rcnn.NUM_ANCHORS
    shapes = dict(data=(1, 3, h, w), im_info=(1, 3),
                  gt_boxes=(1, 2, 5), label=(1, na * fh * fw),
                  bbox_target=(1, 4 * na, fh, fw),
                  bbox_weight=(1, 4 * na, fh, fw))
    ex = net.simple_bind(grad_req="write", **shapes)
    rng = np.random.RandomState(2)
    for n in ex.arg_dict:
        if n not in shapes:
            ex.arg_dict[n][:] = mx.nd.array(
                rng.uniform(-0.01, 0.01,
                            ex.arg_dict[n].shape).astype(np.float32))
    ex.arg_dict["data"][:] = mx.nd.array(
        rng.rand(1, 3, h, w).astype(np.float32))
    ex.arg_dict["im_info"][:] = mx.nd.array(
        np.array([[h, w, 1.0]], np.float32))
    ex.arg_dict["gt_boxes"][:] = mx.nd.array(
        np.array([[[4, 4, 30, 30, 1], [34, 34, 60, 60, 2]]], np.float32))
    lab = rng.randint(-1, 2, (1, na * fh * fw)).astype(np.float32)
    ex.arg_dict["label"][:] = mx.nd.array(lab)
    ex.arg_dict["bbox_target"][:] = mx.nd.array(
        rng.randn(1, 4 * na, fh, fw).astype(np.float32) * 0.1)
    ex.arg_dict["bbox_weight"][:] = mx.nd.array(
        (rng.rand(1, 4 * na, fh, fw) > 0.7).astype(np.float32))
    ex.forward(is_train=True)  # deferred: backward runs fused fwd+bwd
    ex.backward()
    assert all(np.isfinite(o.asnumpy()).all() for o in ex.outputs)
    g = ex.grad_dict["rpn_conv_3x3_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    g2 = ex.grad_dict["conv1_1_weight"].asnumpy()
    assert np.isfinite(g2).all()
