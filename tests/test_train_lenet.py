"""End-to-end LeNet MNIST (BASELINE config 1) — reference
``tests/python/train/test_conv.py`` pattern: small convergence run with an
accuracy threshold."""
import numpy as np

import incubator_mxnet_tpu as mx


def test_lenet_mnist_convergence():
    mx.random.seed(0)
    np.random.seed(0)
    train = mx.io.MNISTIter(batch_size=64, shuffle=True, num_examples=1024,
                            seed=0)
    val = mx.io.MNISTIter(batch_size=64, shuffle=False, num_examples=256,
                          seed=1)
    net = mx.models.lenet(num_classes=10)
    mod = mx.mod.Module(net, context=mx.cpu())
    # lr 0.05: the tanh LeNet saturates into a dead 10%-accuracy state
    # for some init/shuffle streams at lr 0.1 + momentum 0.9 (effective
    # lr 1.0); the smoke test asserts convergence, not lr-robustness
    mod.fit(train, eval_data=val, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(64, 10))
    score = mod.score(train, "acc")[0][1]
    # synthetic MNIST templates are learnable to near-perfect quickly
    assert score > 0.9, "LeNet failed to converge: acc=%.3f" % score
    # val shares the train templates (fixed template seed in MNISTIter),
    # so a converged model must also generalize to it
    val_score = mod.score(val, "acc")[0][1]
    assert val_score > 0.9, "no generalization: val=%.3f" % val_score


def test_model_zoo_shapes():
    # every zoo symbol infers shapes end-to-end
    cases = [
        (mx.models.mlp(), (2, 784)),
        (mx.models.lenet(), (2, 1, 28, 28)),
        (mx.models.alexnet(num_classes=100), (2, 3, 224, 224)),
        (mx.models.resnet(num_layers=20, num_classes=10,
                          image_shape=(3, 32, 32)), (2, 3, 32, 32)),
        (mx.models.get_symbol("resnet50", num_classes=1000),
         (2, 3, 224, 224)),
        (mx.models.vgg(num_layers=11, num_classes=10), (2, 3, 224, 224)),
        (mx.models.inception_bn(num_classes=10), (2, 3, 224, 224)),
    ]
    for net, dshape in cases:
        arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=dshape)
        assert out_shapes[0][0] == 2
        assert all(s is not None for s in arg_shapes)


def test_resnet20_cifar_forward():
    net = mx.models.resnet(num_layers=20, num_classes=10,
                           image_shape=(3, 32, 32))
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 32, 32))
    for name, arr in ex.arg_dict.items():
        if name.endswith("weight"):
            arr[:] = np.random.randn(*arr.shape).astype(np.float32) * 0.05
    x = np.random.rand(2, 3, 32, 32).astype(np.float32)
    ex.forward(is_train=False, data=x,
               softmax_label=np.zeros(2, np.float32))
    out = ex.outputs[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), np.ones(2), rtol=1e-4)


def test_dcgan_symbols():
    """DCGAN generator/discriminator shapes (reference
    example/gan/dcgan.py make_dcgan_sym)."""
    from incubator_mxnet_tpu.models import dcgan

    for size in (32, 64):
        g, d = dcgan.make_dcgan_sym(ngf=8, ndf=8, nc=3, size=size)
        _, go, _ = g.infer_shape(rand=(2, 4, 1, 1))
        assert go == [(2, 3, size, size)]
        _, do, _ = d.infer_shape(data=(2, 3, size, size), label=(2, 1))
        assert do == [(2, 1)]
