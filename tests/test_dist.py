"""Multi-process distributed kvstore proof on localhost.

Reference analog: ``tests/nightly/test_all.sh:55`` running
``tools/launch.py -n 4 python dist_sync_kvstore.py`` — distribution
validated without a cluster via local processes with exact-value asserts.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")


def _run_launch(args, script, timeout=600):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker scripts pin cpu themselves
    cmd = [sys.executable, LAUNCH] + args + [sys.executable, script]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=REPO)
    if proc.returncode != 0:
        raise AssertionError(
            "launch failed rc=%d\nstdout:\n%s\nstderr:\n%s"
            % (proc.returncode, proc.stdout[-4000:], proc.stderr[-4000:]))
    return proc


@pytest.mark.slow
def test_dist_sync_kvstore_4_workers():
    proc = _run_launch(["-n", "4"],
                       os.path.join(REPO, "tests", "dist",
                                    "dist_sync_kvstore.py"))
    assert proc.stdout.count("OK") == 4, proc.stdout


@pytest.mark.slow
def test_dist_sync_kvstore_via_parameter_server():
    """Same exact-value contract, but carried by the PS transport in
    server-merge sync mode (kvstore_dist_server.h:182 merge-then-update)."""
    proc = _run_launch(["-n", "2", "-s", "2"],
                       os.path.join(REPO, "tests", "dist",
                                    "dist_sync_kvstore.py"))
    assert proc.stdout.count("OK") == 2, proc.stdout


@pytest.mark.slow
def test_dist_async_kvstore_2x2():
    proc = _run_launch(["-n", "2", "-s", "2"],
                       os.path.join(REPO, "tests", "dist",
                                    "dist_async_kvstore.py"))
    assert proc.stdout.count("OK") == 2, proc.stdout
