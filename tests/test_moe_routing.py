"""MoE routing invariants (``ops/_moe_routing.py``).

``sparse_dispatch`` scatters only the int32 source-token id per
capacity slot and gathers rows — it is collision-free ONLY because the
(expert, position) pairs of kept assignments are unique (int32 cumsum
positions; a token's top-k experts are distinct).  These tests pin
that invariant by checking the scatter-max dispatch against a naive
scatter-ADD reference: any slot collision would double-count rows in
the reference and the two buffers would diverge.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.ops._moe_routing import (route, sparse_combine,
                                                  sparse_dispatch)


def _dispatch_scatter_add(xf, flat_e, keep, safe_pos, E, cap, top_k):
    """Reference dispatch: scatter-ADD every kept token row into its
    (e, pos) slot.  Equals the shipped gather-based dispatch iff kept
    slots are unique."""
    d = xf.shape[-1]
    n = flat_e.shape[0]
    tok = jnp.arange(n, dtype=jnp.int32) // top_k
    rows = xf[tok] * keep[:, None].astype(xf.dtype)
    slot = flat_e.astype(jnp.int32) * cap + safe_pos.astype(jnp.int32)
    # route dropped assignments to a scratch slot past the real buffer
    slot = jnp.where(keep, slot, E * cap)
    buf = jnp.zeros((E * cap + 1, d), xf.dtype).at[slot].add(rows)
    return buf[:-1].reshape(E, cap, d)


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scatter_max_and_scatter_add_dispatch_agree(top_k, seed):
    rng = np.random.RandomState(seed)
    T, E, d = 32, 4, 8
    cap = 6  # tight: forces drops, exercising the keep mask
    probs = jax.nn.softmax(
        jnp.asarray(rng.randn(T, E).astype(np.float32)), axis=-1)
    xf = jnp.asarray(rng.randn(T, d).astype(np.float32))
    gate_vals, flat_e, onehot, keep, safe_pos = route(probs, top_k, cap)
    got = sparse_dispatch(xf, flat_e, keep, safe_pos, E, cap, top_k)
    want = _dispatch_scatter_add(xf, flat_e, keep, safe_pos, E, cap,
                                 top_k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=0)


def test_kept_slots_are_unique():
    """The invariant itself: no two kept assignments share (e, pos)."""
    rng = np.random.RandomState(7)
    T, E, top_k, cap = 64, 8, 2, 5
    probs = jax.nn.softmax(
        jnp.asarray(rng.randn(T, E).astype(np.float32)), axis=-1)
    _, flat_e, _, keep, safe_pos = route(probs, top_k, cap)
    e = np.asarray(flat_e)[np.asarray(keep)]
    p = np.asarray(safe_pos)[np.asarray(keep)]
    slots = e.astype(np.int64) * cap + p
    assert len(slots) == len(np.unique(slots))
    # positions honor the capacity bound
    assert (p < cap).all() and (p >= 0).all()


def test_dispatch_combine_round_trip_at_loose_capacity():
    """With capacity loose enough that nothing drops, dispatch+combine
    reconstructs each token as the gate-weighted sum of its experts'
    buffer rows (identity experts)."""
    rng = np.random.RandomState(3)
    T, E, d, top_k = 16, 4, 8, 2
    cap = T * top_k  # nothing can overflow
    probs = jax.nn.softmax(
        jnp.asarray(rng.randn(T, E).astype(np.float32)), axis=-1)
    xf = jnp.asarray(rng.randn(T, d).astype(np.float32))
    gate_vals, flat_e, _, keep, safe_pos = route(probs, top_k, cap)
    assert bool(np.asarray(keep).all())
    buf = sparse_dispatch(xf, flat_e, keep, safe_pos, E, cap, top_k)
    out = sparse_combine(buf, flat_e, keep, safe_pos, gate_vals, top_k)
    # identity experts + renormalized gates (sum to 1) => tokens back
    np.testing.assert_allclose(np.asarray(out), np.asarray(xf),
                               rtol=1e-5, atol=1e-6)
